// Native tier-2 solver: bit-blasting CDCL for the probe stack.
//
// The reference leans on Z3 (C++) for every satisfiability question; this
// framework's device probe answers most queries, and this library provides
// the exact fallback for the residue: UNSAT verdicts the probe cannot give,
// and hard SAT instances the directed fuzzer misses.  See
// mythril_tpu/smt/solver.py (tier 2) and mythril_tpu/native/bitblast.py for
// the Python integration; SURVEY.md §2.9 names this component (the z3-solver
// row: "bit-blasted SAT ... kernel + host-side fallback oracle").
//
// Interface: a flat int32 "term tape" (7 ints per node: op, width, a0, a1,
// a2, aux0, aux1) + a little-endian byte pool for constants.  Every node is
// Tseitin-encoded into CNF (LSB-first literal vectors); root nodes are
// asserted true; the CDCL core (two-watched-literal propagation, 1UIP
// learning, VSIDS decisions, Luby restarts, phase saving) decides the
// formula.  Models are returned as packed bits for each VAR node in tape
// order.  Semantics mirror mythril_tpu/smt/concrete_eval.py exactly
// (EVM-style div-by-zero == 0, shifts >= width == 0, ashr saturates).
//
// Build: g++ -O2 -shared -fPIC (driven by mythril_tpu/native/build.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// CDCL SAT core
// ---------------------------------------------------------------------------

using Lit = int32_t;  // (var << 1) | sign ; var 0 is the constant TRUE var
inline Lit mklit(int v, bool neg) { return (v << 1) | (neg ? 1 : 0); }
inline int var_of(Lit l) { return l >> 1; }
inline bool sign_of(Lit l) { return l & 1; }
inline Lit neg(Lit l) { return l ^ 1; }

const Lit LIT_TRUE = 0;   // var 0 positive
const Lit LIT_FALSE = 1;  // var 0 negated

enum Value : int8_t { V_UNDEF = 0, V_TRUE = 1, V_FALSE = 2 };

struct Clause {
  std::vector<Lit> lits;
  bool learned;
};

struct Watcher {
  Clause* clause;
  Lit blocker;
};

class Solver {
 public:
  Solver() {
    new_var();  // var 0 = constant true
    enqueue(LIT_TRUE, nullptr);
  }

  ~Solver() {
    for (Clause* c : clauses_) delete c;
    for (Clause* c : learned_) delete c;
  }

  int new_var() {
    int v = (int)assigns_.size();
    assigns_.push_back(V_UNDEF);
    level_.push_back(-1);
    reason_.push_back(nullptr);
    activity_.push_back(0.0);
    phase_.push_back(false);
    watches_.emplace_back();
    watches_.emplace_back();
    heap_pos_.push_back(-1);
    heap_insert(v);
    return v;
  }

  size_t num_clauses() const { return clauses_.size() + learned_.size(); }

  Value value(Lit l) const {
    Value v = (Value)assigns_[var_of(l)];
    if (v == V_UNDEF) return V_UNDEF;
    if (sign_of(l)) return v == V_TRUE ? V_FALSE : V_TRUE;
    return v;
  }

  // Add a clause; returns false if the formula became trivially unsat.
  bool add_clause(std::vector<Lit> lits) {
    // top-level simplification: remove false lits, drop satisfied clauses
    std::sort(lits.begin(), lits.end());
    lits.erase(std::unique(lits.begin(), lits.end()), lits.end());
    std::vector<Lit> out;
    for (Lit l : lits) {
      if (std::binary_search(lits.begin(), lits.end(), neg(l)) && var_of(l) != 0)
        return true;  // tautology
      Value v = value(l);
      if (v == V_TRUE && level_[var_of(l)] <= 0) return true;
      if (v == V_FALSE && level_[var_of(l)] <= 0) continue;
      out.push_back(l);
    }
    if (out.empty()) return ok_ = false;
    if (out.size() == 1) {
      if (value(out[0]) == V_FALSE) return ok_ = false;
      if (value(out[0]) == V_UNDEF) enqueue(out[0], nullptr);
      return ok_;
    }
    attach(new_clause(std::move(out), false));
    return ok_;
  }

  // Return to decision level 0 so clauses can be added after a solve()
  // left the trail at a satisfying (or partial) assignment.
  void reset_root() { backtrack(0); }

  // status: 1 sat, 0 unsat (w.r.t. assumptions when given), -1 budget
  // exceeded.  Assumptions are decided first, MiniSat-style (each on its
  // own level; already-true ones get a dummy level) — learned clauses are
  // consequences of the CNF alone, so they persist soundly across calls
  // with different assumption sets (the incremental Optimize session).
  int solve(double deadline_wall, const std::vector<Lit>& assumptions = {}) {
    backtrack(0);
    if (!ok_) return 0;
    if (propagate() != nullptr) return 0;
    int64_t conflicts = 0;
    int restart_idx = 0;
    int64_t restart_budget = luby(restart_idx) * 128;
    for (;;) {
      Clause* confl = propagate();
      if (confl != nullptr) {
        conflicts++;
        if (decision_level() == 0) return 0;
        if (decision_level() <= (int)assumptions.size()) {
          // conflict entirely under the assumption prefix: analyze() would
          // need to flip an assumption — UNSAT under these assumptions.
          // (Learned-clause quality is irrelevant here; just report.)
          backtrack(0);
          return 0;
        }
        std::vector<Lit> learnt;
        int bt;
        analyze(confl, learnt, bt);
        backtrack(bt);
        if (learnt.size() == 1) {
          enqueue(learnt[0], nullptr);
        } else {
          Clause* c = new_clause(std::move(learnt), true);
          attach(c);
          enqueue(c->lits[0], c);
        }
        var_decay();
        if ((conflicts & 1023) == 0) {
          if (wall_now() > deadline_wall) return -1;
          if (num_clauses() > 6000000) return -1;
        }
        if (conflicts > restart_budget) {
          conflicts = 0;
          restart_budget = luby(++restart_idx) * 128;
          backtrack(0);
        }
      } else {
        Lit next = -1;
        while (decision_level() < (int)assumptions.size()) {
          Lit a = assumptions[decision_level()];
          Value v = value(a);
          if (v == V_TRUE) {
            trail_lim_.push_back((int)trail_.size());  // dummy level
            continue;
          }
          if (v == V_FALSE) {
            backtrack(0);
            return 0;  // UNSAT under assumptions
          }
          next = a;
          break;
        }
        if (next == -1) next = decide();
        if (next == -1) return 1;  // all assigned: SAT
        trail_lim_.push_back((int)trail_.size());
        enqueue(next, nullptr);
      }
    }
  }

  bool model_value(int v) const { return assigns_[v] == V_TRUE; }
  bool ok() const { return ok_; }

 private:
  bool ok_ = true;
  std::vector<int8_t> assigns_;
  std::vector<int> level_;
  std::vector<Clause*> reason_;
  std::vector<double> activity_;
  std::vector<bool> phase_;
  std::vector<std::vector<Watcher>> watches_;  // indexed by lit
  std::vector<Lit> trail_;
  std::vector<int> trail_lim_;
  size_t qhead_ = 0;
  std::vector<Clause*> clauses_, learned_;
  double var_inc_ = 1.0;
  // binary max-heap over activity for decisions
  std::vector<int> heap_;
  std::vector<int> heap_pos_;

  static double wall_now() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + ts.tv_nsec * 1e-9;
  }

  static int64_t luby(int i) {
    // Luby sequence (1,1,2,1,1,2,4,...)
    int k = 1;
    while ((1 << (k + 1)) - 1 <= i + 1) k++;
    while (i + 1 != (1 << k) - 1) {
      i = i - (1 << (k - 1)) + 1 - 1;
      k--;
      while ((1 << (k + 1)) - 1 <= i + 1) k++;
    }
    return 1ll << (k - 1);
  }

  int decision_level() const { return (int)trail_lim_.size(); }

  Clause* new_clause(std::vector<Lit> lits, bool learned) {
    Clause* c = new Clause{std::move(lits), learned};
    (learned ? learned_ : clauses_).push_back(c);
    return c;
  }

  void attach(Clause* c) {
    watches_[neg(c->lits[0])].push_back({c, c->lits[1]});
    watches_[neg(c->lits[1])].push_back({c, c->lits[0]});
  }

  void enqueue(Lit l, Clause* from) {
    int v = var_of(l);
    assigns_[v] = sign_of(l) ? V_FALSE : V_TRUE;
    level_[v] = decision_level();
    reason_[v] = from;
    phase_[v] = !sign_of(l);
    trail_.push_back(l);
  }

  Clause* propagate() {
    while (qhead_ < trail_.size()) {
      Lit p = trail_[qhead_++];
      auto& ws = watches_[p];
      size_t i = 0, j = 0;
      while (i < ws.size()) {
        Watcher w = ws[i++];
        if (value(w.blocker) == V_TRUE) {
          ws[j++] = w;
          continue;
        }
        Clause& c = *w.clause;
        // make sure c.lits[1] is the false literal (neg(p))
        if (c.lits[0] == neg(p)) std::swap(c.lits[0], c.lits[1]);
        if (value(c.lits[0]) == V_TRUE) {
          ws[j++] = {w.clause, c.lits[0]};
          continue;
        }
        // look for a new watch
        bool found = false;
        for (size_t k = 2; k < c.lits.size(); k++) {
          if (value(c.lits[k]) != V_FALSE) {
            std::swap(c.lits[1], c.lits[k]);
            watches_[neg(c.lits[1])].push_back({w.clause, c.lits[0]});
            found = true;
            break;
          }
        }
        if (found) continue;
        // unit or conflict
        ws[j++] = w;
        if (value(c.lits[0]) == V_FALSE) {
          while (i < ws.size()) ws[j++] = ws[i++];
          ws.resize(j);
          qhead_ = trail_.size();
          return w.clause;
        }
        enqueue(c.lits[0], w.clause);
      }
      ws.resize(j);
    }
    return nullptr;
  }

  void bump(int v) {
    if ((activity_[v] += var_inc_) > 1e100) {
      for (auto& a : activity_) a *= 1e-100;
      var_inc_ *= 1e-100;
    }
    heap_update(v);
  }

  void var_decay() { var_inc_ *= (1.0 / 0.95); }

  void analyze(Clause* confl, std::vector<Lit>& out, int& bt_level) {
    out.clear();
    out.push_back(-1);  // slot for the asserting literal
    std::vector<bool> seen(assigns_.size(), false);
    int counter = 0;
    Lit p = -1;
    size_t idx = trail_.size();
    for (;;) {
      for (size_t k = (p == -1 ? 0 : 1); k < confl->lits.size(); k++) {
        Lit q = confl->lits[k];
        int v = var_of(q);
        if (!seen[v] && level_[v] > 0) {
          seen[v] = true;
          bump(v);
          if (level_[v] >= decision_level())
            counter++;
          else
            out.push_back(q);
        }
      }
      // next literal on trail at current level
      while (!seen[var_of(trail_[--idx])]) {
      }
      p = trail_[idx];
      confl = reason_[var_of(p)];
      seen[var_of(p)] = false;
      if (--counter == 0) break;
    }
    out[0] = neg(p);
    // backtrack level = max level among the rest
    bt_level = 0;
    int max_i = 1;
    for (size_t k = 1; k < out.size(); k++) {
      if (level_[var_of(out[k])] > bt_level) {
        bt_level = level_[var_of(out[k])];
        max_i = (int)k;
      }
    }
    if (out.size() > 1) std::swap(out[1], out[max_i]);
  }

  void backtrack(int lvl) {
    if (decision_level() <= lvl) return;
    for (int i = (int)trail_.size() - 1; i >= trail_lim_[lvl]; i--) {
      int v = var_of(trail_[i]);
      assigns_[v] = V_UNDEF;
      reason_[v] = nullptr;
      heap_insert(v);
    }
    trail_.resize(trail_lim_[lvl]);
    trail_lim_.resize(lvl);
    qhead_ = trail_.size();
  }

  Lit decide() {
    while (!heap_.empty()) {
      int v = heap_pop();
      if (assigns_[v] == V_UNDEF) return mklit(v, !phase_[v]);
    }
    return -1;
  }

  // -- activity heap
  void heap_swap(int i, int j) {
    std::swap(heap_[i], heap_[j]);
    heap_pos_[heap_[i]] = i;
    heap_pos_[heap_[j]] = j;
  }
  void heap_up(int i) {
    while (i > 0) {
      int p = (i - 1) / 2;
      if (activity_[heap_[i]] <= activity_[heap_[p]]) break;
      heap_swap(i, p);
      i = p;
    }
  }
  void heap_down(int i) {
    for (;;) {
      int l = 2 * i + 1, r = 2 * i + 2, m = i;
      if (l < (int)heap_.size() && activity_[heap_[l]] > activity_[heap_[m]]) m = l;
      if (r < (int)heap_.size() && activity_[heap_[r]] > activity_[heap_[m]]) m = r;
      if (m == i) break;
      heap_swap(i, m);
      i = m;
    }
  }
  void heap_insert(int v) {
    if (heap_pos_[v] != -1) return;
    heap_pos_[v] = (int)heap_.size();
    heap_.push_back(v);
    heap_up(heap_pos_[v]);
  }
  void heap_update(int v) {
    if (heap_pos_[v] != -1) heap_up(heap_pos_[v]);
  }
  int heap_pop() {
    int v = heap_[0];
    heap_swap(0, (int)heap_.size() - 1);
    heap_.pop_back();
    heap_pos_[v] = -1;
    if (!heap_.empty()) heap_down(0);
    return v;
  }
};

// ---------------------------------------------------------------------------
// Tseitin circuit builder with constant folding
// ---------------------------------------------------------------------------

class Circuit {
 public:
  explicit Circuit(Solver& s) : s_(s) {}

  Lit lit_and(Lit a, Lit b) {
    if (a == LIT_FALSE || b == LIT_FALSE) return LIT_FALSE;
    if (a == LIT_TRUE) return b;
    if (b == LIT_TRUE) return a;
    if (a == b) return a;
    if (a == neg(b)) return LIT_FALSE;
    Lit o = mklit(s_.new_var(), false);
    s_.add_clause({neg(a), neg(b), o});
    s_.add_clause({a, neg(o)});
    s_.add_clause({b, neg(o)});
    return o;
  }

  Lit lit_or(Lit a, Lit b) { return neg(lit_and(neg(a), neg(b))); }

  Lit lit_xor(Lit a, Lit b) {
    if (a == LIT_FALSE) return b;
    if (b == LIT_FALSE) return a;
    if (a == LIT_TRUE) return neg(b);
    if (b == LIT_TRUE) return neg(a);
    if (a == b) return LIT_FALSE;
    if (a == neg(b)) return LIT_TRUE;
    Lit o = mklit(s_.new_var(), false);
    s_.add_clause({neg(a), neg(b), neg(o)});
    s_.add_clause({a, b, neg(o)});
    s_.add_clause({neg(a), b, o});
    s_.add_clause({a, neg(b), o});
    return o;
  }

  Lit lit_ite(Lit c, Lit t, Lit e) {
    if (c == LIT_TRUE) return t;
    if (c == LIT_FALSE) return e;
    if (t == e) return t;
    if (t == LIT_TRUE && e == LIT_FALSE) return c;
    if (t == LIT_FALSE && e == LIT_TRUE) return neg(c);
    Lit o = mklit(s_.new_var(), false);
    s_.add_clause({neg(c), neg(t), o});
    s_.add_clause({neg(c), t, neg(o)});
    s_.add_clause({c, neg(e), o});
    s_.add_clause({c, e, neg(o)});
    return o;
  }

  Lit big_and(const std::vector<Lit>& xs) {
    std::vector<Lit> body;
    for (Lit x : xs) {
      if (x == LIT_FALSE) return LIT_FALSE;
      if (x != LIT_TRUE) body.push_back(x);
    }
    if (body.empty()) return LIT_TRUE;
    if (body.size() == 1) return body[0];
    Lit o = mklit(s_.new_var(), false);
    std::vector<Lit> all{o};
    for (Lit x : body) {
      s_.add_clause({x, neg(o)});
      all.push_back(neg(x));
    }
    s_.add_clause(all);
    return o;
  }

  Lit big_or(std::vector<Lit> xs) {
    for (auto& x : xs) x = neg(x);
    return neg(big_and(xs));
  }

  // bit-vector values are LSB-first literal vectors
  using BV = std::vector<Lit>;

  Lit eq(const BV& a, const BV& b) {
    std::vector<Lit> bits;
    for (size_t i = 0; i < a.size(); i++) bits.push_back(neg(lit_xor(a[i], b[i])));
    return big_and(bits);
  }

  BV add(const BV& a, const BV& b, Lit cin = LIT_FALSE) {
    BV out(a.size());
    Lit c = cin;
    for (size_t i = 0; i < a.size(); i++) {
      Lit axb = lit_xor(a[i], b[i]);
      out[i] = lit_xor(axb, c);
      // carry = (a&b) | (c & (a^b))
      c = lit_or(lit_and(a[i], b[i]), lit_and(c, axb));
    }
    return out;
  }

  BV bvnot(const BV& a) {
    BV out(a.size());
    for (size_t i = 0; i < a.size(); i++) out[i] = neg(a[i]);
    return out;
  }

  BV sub(const BV& a, const BV& b) { return add(a, bvnot(b), LIT_TRUE); }

  BV bvneg(const BV& a) { return add(bvnot(a), constant(0, a.size()), LIT_TRUE); }

  Lit ult(const BV& a, const BV& b) {
    Lit lt = LIT_FALSE;
    for (size_t i = 0; i < a.size(); i++) {
      Lit eqb = neg(lit_xor(a[i], b[i]));
      lt = lit_or(lit_and(neg(a[i]), b[i]), lit_and(eqb, lt));
    }
    return lt;
  }

  Lit slt(const BV& a, const BV& b) {
    Lit sa = a.back(), sb = b.back();
    Lit both = neg(lit_xor(sa, sb));
    return lit_or(lit_and(sa, neg(sb)), lit_and(both, ult(a, b)));
  }

  BV mux(Lit c, const BV& t, const BV& e) {
    BV out(t.size());
    for (size_t i = 0; i < t.size(); i++) out[i] = lit_ite(c, t[i], e[i]);
    return out;
  }

  BV mul(const BV& a, const BV& b) {
    // Column-compression (Dadda-style) multiplier: bucket partial products
    // by output column, 3:2 full-adder compression, one final ripple add.
    // Versus row-ripple accumulation this emits ~1.5x fewer adders for
    // zext'd operands (zero partial products fold away entirely) and a far
    // shallower carry structure — the 512-bit overflow-predicate multiply
    // (BVMulNoOverflow on 256-bit EVM words) is the motivating case.
    size_t w = a.size();
    std::vector<std::vector<Lit>> cols(w);
    for (size_t i = 0; i < w; i++) {
      if (b[i] == LIT_FALSE) continue;
      for (size_t j = 0; i + j < w; j++) {
        Lit pp = lit_and(a[j], b[i]);
        if (pp != LIT_FALSE) cols[i + j].push_back(pp);
      }
    }
    BV row0(w, LIT_FALSE), row1(w, LIT_FALSE);
    for (size_t k = 0; k < w; k++) {
      auto& c = cols[k];
      size_t head = 0;
      while (c.size() - head >= 3) {
        Lit x = c[head], y = c[head + 1], z = c[head + 2];
        head += 3;
        Lit xy = lit_xor(x, y);
        c.push_back(lit_xor(xy, z));  // sum stays in this column
        Lit carry = lit_or(lit_and(x, y), lit_and(z, xy));
        if (k + 1 < w && carry != LIT_FALSE) cols[k + 1].push_back(carry);
      }
      if (c.size() - head == 2) {
        // half-adder: defer the pairwise add to the final ripple rows
        row0[k] = c[head];
        row1[k] = c[head + 1];
      } else if (c.size() - head == 1) {
        row0[k] = c[head];
      }
      c.clear();
    }
    return add(row0, row1);
  }

  // q, r as fresh variables constrained by a == q*b + r (2w-bit), r < b;
  // b == 0 yields q = 0, r = 0 (EVM semantics, concrete_eval.py:152-177)
  void udivrem(const BV& a, const BV& b, BV& q, BV& r) {
    size_t w = a.size();
    q = fresh(w);
    r = fresh(w);
    Lit bz = is_zero(b);
    for (size_t i = 0; i < w; i++) {
      s_.add_clause({neg(bz), neg(q[i])});
      s_.add_clause({neg(bz), neg(r[i])});
    }
    BV a2 = zext(a, 2 * w), b2 = zext(b, 2 * w), q2 = zext(q, 2 * w),
       r2 = zext(r, 2 * w);
    BV prod = mul(q2, b2);
    BV sum = add(prod, r2);
    Lit exact = eq(sum, a2);
    Lit bounded = ult(r, b);
    s_.add_clause({bz, exact});
    s_.add_clause({bz, bounded});
  }

  Lit is_zero(const BV& a) {
    std::vector<Lit> bits;
    for (Lit x : a) bits.push_back(neg(x));
    return big_and(bits);
  }

  BV constant(uint64_t v, size_t w) {
    BV out(w);
    for (size_t i = 0; i < w; i++)
      out[i] = (i < 64 && ((v >> i) & 1)) ? LIT_TRUE : LIT_FALSE;
    return out;
  }

  BV from_bytes(const uint8_t* bytes, size_t nbytes, size_t w) {
    BV out(w, LIT_FALSE);
    for (size_t i = 0; i < w && i / 8 < nbytes; i++)
      if ((bytes[i / 8] >> (i % 8)) & 1) out[i] = LIT_TRUE;
    return out;
  }

  BV fresh(size_t w) {
    BV out(w);
    for (size_t i = 0; i < w; i++) out[i] = mklit(s_.new_var(), false);
    return out;
  }

  BV zext(const BV& a, size_t w) {
    BV out = a;
    out.resize(w, LIT_FALSE);
    return out;
  }

  BV sext(const BV& a, size_t w) {
    BV out = a;
    out.resize(w, a.back());
    return out;
  }

  // Barrel shifters; amt semantics follow concrete_eval.py:191-193.
  BV shl(const BV& a, const BV& amt) { return shift(a, amt, false, LIT_FALSE); }
  BV lshr(const BV& a, const BV& amt) { return shift(a, amt, true, LIT_FALSE); }
  BV ashr(const BV& a, const BV& amt) { return shift(a, amt, true, a.back(), true); }

 private:
  BV shift(const BV& a, const BV& amt, bool right, Lit fill, bool saturate = false) {
    size_t w = a.size();
    int stages = 0;
    while ((1u << stages) < w) stages++;
    BV cur = a;
    for (int s = 0; s < stages; s++) {
      size_t k = 1u << s;
      BV shifted(w, fill);
      for (size_t i = 0; i < w; i++) {
        if (right) {
          if (i + k < w) shifted[i] = cur[i + k];
        } else {
          if (i >= k) shifted[i] = cur[i - k];
        }
      }
      cur = mux(amt[s], shifted, cur);
    }
    // out-of-range: any amount bit at or above `stages` set -> amount >= 2^stages >= w
    // (for non-power-of-two widths also compare the in-stage part against w)
    std::vector<Lit> high;
    for (size_t i = stages; i < amt.size(); i++) high.push_back(amt[i]);
    Lit oor = big_or(high);
    if ((1u << stages) != w) {
      // stages cover up to 2^stages-1 >= w: also out of range when the low
      // bits alone reach w
      BV low(amt.begin(), amt.begin() + stages);
      Lit low_ge_w = neg(ult(zext(low, w), constant(w, w)));
      oor = lit_or(oor, low_ge_w);
    }
    BV oob(w, saturate ? fill : LIT_FALSE);
    return mux(oor, oob, cur);
  }

  Solver& s_;
};

// ---------------------------------------------------------------------------
// Tape interpreter
// ---------------------------------------------------------------------------

enum Op : int32_t {
  OP_CONST = 0,
  OP_VAR = 1,
  OP_EQ = 2,
  OP_AND = 3,
  OP_OR = 4,
  OP_NOT = 5,
  OP_XOR = 6,
  OP_ITE = 7,
  OP_ADD = 8,
  OP_SUB = 9,
  OP_MUL = 10,
  OP_UDIV = 11,
  OP_UREM = 12,
  OP_SDIV = 13,
  OP_SREM = 14,
  OP_BAND = 15,
  OP_BOR = 16,
  OP_BXOR = 17,
  OP_BNOT = 18,
  OP_NEG = 19,
  OP_SHL = 20,
  OP_LSHR = 21,
  OP_ASHR = 22,
  OP_CONCAT = 23,
  OP_EXTRACT = 24,
  OP_ZEXT = 25,
  OP_SEXT = 26,
  OP_ULT = 27,
  OP_ULE = 28,
  OP_SLT = 29,
  OP_SLE = 30,
};

const int REC = 7;  // int32s per tape record

// A blasted tape kept alive for incremental solving: the CNF (with all
// learned clauses) persists across bb_solve_assume calls, so a sequence of
// bound queries over the same formula — the Optimize refinement loop — pays
// the circuit construction once instead of once per query.
struct Blasted {
  Solver solver;
  std::vector<Circuit::BV> val;
  std::vector<int32_t> tape;  // copy (REC per node) for model packing
  int64_t n_nodes = 0;
  int status = 1;  // 1 usable, 0 globally unsat, -1 unsupported
};

// Appends `n_new` records to b (argument indices may reference any node
// below the new total) and asserts `roots`; returns 1 ok, 0 unsat, -1
// unsupported.  Called with an empty Blasted this is the original full
// blast; called again via bb_extend it grows an open session in place
// (CEGAR congruence refinement) while keeping all learned clauses.
static int blast_append(Blasted& b, const int32_t* tape, int64_t n_new,
                        const uint8_t* consts, const int32_t* roots,
                        int64_t n_roots) {
  Solver& solver = b.solver;
  Circuit cir(solver);
  const int64_t base = b.n_nodes;
  b.val.resize(base + n_new);
  b.tape.insert(b.tape.end(), tape, tape + n_new * REC);
  b.n_nodes = base + n_new;
  std::vector<Circuit::BV>& val = b.val;
  for (int64_t ii = 0; ii < n_new; ii++) {
    const int64_t i = base + ii;
    const int32_t* r = tape + ii * REC;
    int32_t op = r[0], w = r[1], a0 = r[2], a1 = r[3], a2 = r[4], x0 = r[5],
            x1 = r[6];
    auto A = [&](int32_t k) -> const Circuit::BV& { return val[k]; };
    switch (op) {
      case OP_CONST:
        val[i] = cir.from_bytes(consts + x0, (size_t)x1, w);
        break;
      case OP_VAR:
        val[i] = cir.fresh(w);
        break;
      case OP_EQ:
        val[i] = {cir.eq(A(a0), A(a1))};
        break;
      case OP_AND:
        val[i] = {cir.lit_and(A(a0)[0], A(a1)[0])};
        break;
      case OP_OR:
        val[i] = {cir.lit_or(A(a0)[0], A(a1)[0])};
        break;
      case OP_NOT:
        val[i] = {neg(A(a0)[0])};
        break;
      case OP_XOR:
        val[i] = {cir.lit_xor(A(a0)[0], A(a1)[0])};
        break;
      case OP_ITE:
        val[i] = cir.mux(A(a0)[0], A(a1), A(a2));
        break;
      case OP_ADD:
        val[i] = cir.add(A(a0), A(a1));
        break;
      case OP_SUB:
        val[i] = cir.sub(A(a0), A(a1));
        break;
      case OP_MUL:
        val[i] = cir.mul(A(a0), A(a1));
        break;
      case OP_UDIV:
      case OP_UREM: {
        Circuit::BV q, rr;
        cir.udivrem(A(a0), A(a1), q, rr);
        val[i] = (op == OP_UDIV) ? q : rr;
        break;
      }
      case OP_SDIV:
      case OP_SREM: {
        const Circuit::BV &a = A(a0), &b = A(a1);
        Lit sa = a.back(), sb = b.back();
        Circuit::BV absa = cir.mux(sa, cir.bvneg(a), a);
        Circuit::BV absb = cir.mux(sb, cir.bvneg(b), b);
        Circuit::BV q, rr;
        cir.udivrem(absa, absb, q, rr);
        if (op == OP_SDIV) {
          Lit flip = cir.lit_xor(sa, sb);
          val[i] = cir.mux(flip, cir.bvneg(q), q);
        } else {
          val[i] = cir.mux(sa, cir.bvneg(rr), rr);
        }
        break;
      }
      case OP_BAND:
      case OP_BOR:
      case OP_BXOR: {
        const Circuit::BV &a = A(a0), &b = A(a1);
        Circuit::BV out(w);
        for (int k = 0; k < w; k++)
          out[k] = (op == OP_BAND)  ? cir.lit_and(a[k], b[k])
                   : (op == OP_BOR) ? cir.lit_or(a[k], b[k])
                                    : cir.lit_xor(a[k], b[k]);
        val[i] = out;
        break;
      }
      case OP_BNOT:
        val[i] = cir.bvnot(A(a0));
        break;
      case OP_NEG:
        val[i] = cir.bvneg(A(a0));
        break;
      case OP_SHL:
        val[i] = cir.shl(A(a0), A(a1));
        break;
      case OP_LSHR:
        val[i] = cir.lshr(A(a0), A(a1));
        break;
      case OP_ASHR:
        val[i] = cir.ashr(A(a0), A(a1));
        break;
      case OP_CONCAT: {
        // arg0 is the HIGH part (concrete_eval.py:107-108)
        Circuit::BV out = A(a1);
        out.insert(out.end(), A(a0).begin(), A(a0).end());
        val[i] = out;
        break;
      }
      case OP_EXTRACT: {
        int hi = x0, lo = x1;
        val[i] = Circuit::BV(A(a0).begin() + lo, A(a0).begin() + hi + 1);
        break;
      }
      case OP_ZEXT:
        val[i] = cir.zext(A(a0), w);
        break;
      case OP_SEXT:
        val[i] = cir.sext(A(a0), w);
        break;
      case OP_ULT:
        val[i] = {cir.ult(A(a0), A(a1))};
        break;
      case OP_ULE:
        val[i] = {neg(cir.ult(A(a1), A(a0)))};
        break;
      case OP_SLT:
        val[i] = {cir.slt(A(a0), A(a1))};
        break;
      case OP_SLE:
        val[i] = {neg(cir.slt(A(a1), A(a0)))};
        break;
      default:
        return -1;  // unsupported op
    }
    if (!solver.ok()) return 0;
    if (solver.num_clauses() > 6000000) return -1;
  }

  for (int64_t k = 0; k < n_roots; k++) {
    if (!solver.add_clause({val[roots[k]][0]})) return 0;
  }
  return 1;
}

static int blast(Blasted& b, const int32_t* tape, int64_t n_nodes,
                 const uint8_t* consts, const int32_t* roots, int64_t n_roots) {
  return blast_append(b, tape, n_nodes, consts, roots, n_roots);
}

// Pack VAR models in tape order; returns 1, or -1 if model_cap is short.
static int pack_model(const Blasted& b, uint8_t* model_out, int64_t model_cap) {
  int64_t off = 0;
  for (int64_t i = 0; i < b.n_nodes; i++) {
    const int32_t* r = b.tape.data() + i * REC;
    if (r[0] != OP_VAR) continue;
    int w = r[1];
    int nbytes = (w + 7) / 8;
    if (off + nbytes > model_cap) return -1;
    for (int k = 0; k < nbytes; k++) model_out[off + k] = 0;
    for (int bit = 0; bit < w; bit++) {
      Lit l = b.val[i][bit];
      bool bv;
      if (l == LIT_TRUE)
        bv = true;
      else if (l == LIT_FALSE)
        bv = false;
      else
        bv = sign_of(l) ? !b.solver.model_value(var_of(l))
                        : b.solver.model_value(var_of(l));
      if (bv) model_out[off + bit / 8] |= (1 << (bit % 8));
    }
    off += nbytes;
  }
  return 1;
}

static double wall_deadline(double timeout_s) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9 + timeout_s;
}

}  // namespace

extern "C" {

// status: 1 sat (model filled), 0 unsat, -1 unknown (unsupported op /
// budget / timeout).  model_out receives, for each VAR node in tape order,
// ceil(width/8) bytes little-endian.
int32_t bb_solve(const int32_t* tape, int64_t n_nodes, const uint8_t* consts,
                 int64_t consts_len, const int32_t* roots, int64_t n_roots,
                 double timeout_s, uint8_t* model_out, int64_t model_cap) {
  (void)consts_len;
  Blasted b;
  int st = blast(b, tape, n_nodes, consts, roots, n_roots);
  if (st != 1) return st;
  int status = b.solver.solve(wall_deadline(timeout_s));
  if (status != 1) return status;
  return pack_model(b, model_out, model_cap);
}

// ---------------------------------------------------------------------------
// Incremental session (Optimize bound refinement): blast once, then answer
// many queries under assumptions.  Assumption encoding per int64 element:
// (node_id << 16) | (bit_index << 1) | value — node must be an OP_VAR.
// ---------------------------------------------------------------------------

void* bb_open(const int32_t* tape, int64_t n_nodes, const uint8_t* consts,
              int64_t consts_len, const int32_t* roots, int64_t n_roots) {
  (void)consts_len;
  Blasted* b = new Blasted();
  b->status = blast(*b, tape, n_nodes, consts, roots, n_roots);
  if (b->status == -1) {
    delete b;
    return nullptr;
  }
  return b;
}

// Grow an open session in place: append `n_new` records (congruence pairs
// over EXISTING nodes; no OP_CONST/OP_VAR expected but both are handled)
// and assert `roots`.  Learned clauses persist — they are consequences of
// the original CNF and adding clauses cannot invalidate them.  Returns 1
// ok, 0 formula now unsat, -1 unusable.
int32_t bb_extend(void* handle, const int32_t* tape, int64_t n_new,
                  const uint8_t* consts, int64_t consts_len,
                  const int32_t* roots, int64_t n_roots) {
  (void)consts_len;
  Blasted* b = static_cast<Blasted*>(handle);
  if (b == nullptr || b->status == -1) return -1;
  if (b->status == 0) return 0;
  b->solver.reset_root();
  int st = blast_append(*b, tape, n_new, consts, roots, n_roots);
  if (st != 1) b->status = st;
  return st;
}

int32_t bb_solve_assume(void* handle, const int64_t* assume, int64_t n_assume,
                        double timeout_s, uint8_t* model_out,
                        int64_t model_cap) {
  Blasted* b = static_cast<Blasted*>(handle);
  if (b == nullptr) return -1;
  if (b->status == 0) return 0;  // globally unsat at blast time
  std::vector<Lit> assumptions;
  assumptions.reserve((size_t)n_assume);
  for (int64_t k = 0; k < n_assume; k++) {
    int64_t a = assume[k];
    int64_t node = a >> 16;
    int bit = (int)((a >> 1) & 0x7FFF);
    bool value = (a & 1) != 0;
    if (node < 0 || node >= b->n_nodes) return -1;
    if (b->tape[node * REC] != OP_VAR) return -1;
    if (bit >= (int)b->val[node].size()) return -1;
    Lit l = b->val[node][bit];
    if (l == LIT_TRUE || l == LIT_FALSE) {
      if ((l == LIT_TRUE) != value) return 0;
      continue;
    }
    assumptions.push_back(value ? l : neg(l));
  }
  int status = b->solver.solve(wall_deadline(timeout_s), assumptions);
  if (status != 1) return status;
  return pack_model(*b, model_out, model_cap);
}

void bb_close(void* handle) { delete static_cast<Blasted*>(handle); }

}  // extern "C"
