"""Engine telemetry embedded into reports.

Reference parity: mythril/laser/execution_info.py:4-11 — engines expose
``ExecutionInfo`` objects whose ``as_dict`` payloads are merged into the
jsonv2 report meta (mythril/analysis/report.py:319-320).  This build ships
two concrete infos: engine totals and solver statistics.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict


class ExecutionInfo(ABC):
    @abstractmethod
    def as_dict(self) -> Dict:
        """Dictionary merged into the report's ``mythril_execution_info``."""


class EngineStatsInfo(ExecutionInfo):
    """Totals from one symbolic-execution run."""

    def __init__(self, laser) -> None:
        self.total_states = laser.total_states
        self.executed_instructions = laser.executed_instruction_count

    def as_dict(self) -> Dict:
        return {
            "total_states": self.total_states,
            "executed_instructions": self.executed_instructions,
        }


class SolverStatsInfo(ExecutionInfo):
    """Snapshot of the process-wide solver counters."""

    def as_dict(self) -> Dict:
        from mythril_tpu.smt.solver import SolverStatistics

        stats = SolverStatistics()
        return {
            "solver_query_count": stats.query_count,
            "solver_time_s": round(stats.solver_time, 3),
            "probe_hits": stats.probe_hits,
            "cdcl_calls": stats.cdcl_calls,
            # completeness boundary: prune decisions taken on UNKNOWN —
            # nonzero means recall may have been lost to solver budgets
            "unknown_as_unsat": stats.unknown_as_unsat,
        }


class CalibrationInfo(ExecutionInfo):
    """Measured dispatch RTT and the break-evens rescaled from it."""

    def as_dict(self) -> Dict:
        from mythril_tpu.support.calibration import telemetry

        cal = telemetry()
        return {"calibration": cal} if cal else {}


class FrontierStatsInfo(ExecutionInfo):
    """Where device-resident execution stopped and why (parks by opcode
    prioritize the next device handlers; see frontier/stats.py)."""

    def as_dict(self) -> Dict:
        from mythril_tpu.frontier.stats import FrontierStatistics

        return {"frontier": FrontierStatistics().as_dict()}
