"""EVMContract: container for runtime + creation bytecode.

Reference parity: mythril/ethereum/evmcontract.py:14-115 (library-placeholder
scrubbing included; the ZODB persistence base is dropped as legacy).
"""

from __future__ import annotations

import re

from mythril_tpu.frontend.disassembler import Disassembly
from mythril_tpu.support.support_utils import get_code_hash


class EVMContract:
    def __init__(
        self,
        code: str = "",
        creation_code: str = "",
        name: str = "Unknown",
        enable_online_lookup: bool = False,
    ):
        # scrub unresolved library placeholders __LibName____ -> zero address
        creation_code = re.sub(r"(_{2}.{38})", "0" * 40, creation_code)
        code = re.sub(r"(_{2}.{38})", "0" * 40, code)

        self.creation_code = creation_code
        self.name = name
        self.code = code
        self.disassembly = Disassembly(code, enable_online_lookup=enable_online_lookup) if code else None
        self.creation_disassembly = (
            Disassembly(creation_code, enable_online_lookup=enable_online_lookup)
            if creation_code
            else None
        )

    @property
    def bytecode_hash(self) -> str:
        return get_code_hash(self.code)

    @property
    def creation_bytecode_hash(self) -> str:
        return get_code_hash(self.creation_code)

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "code": self.code,
            "creation_code": self.creation_code,
            "disassembly": self.disassembly.get_easm() if self.disassembly else "",
        }

    def get_easm(self) -> str:
        return self.disassembly.get_easm() if self.disassembly else ""

    def get_creation_easm(self) -> str:
        return self.creation_disassembly.get_easm() if self.creation_disassembly else ""

    def matches_expression(self, expression: str) -> bool:
        """Mini query language: func#name#, code#hex# joined by 'and'/'or'."""
        str_eval = ""
        tokens = re.split(r"\s+(and|or)\s+", expression, flags=re.IGNORECASE)
        for token in tokens:
            if token.lower() in ("and", "or"):
                str_eval += f" {token.lower()} "
                continue
            m = re.match(r"func#([a-zA-Z0-9\s_,(\\)\[\]]+)#", token)
            if m:
                sign_hash = "0x" + __import__(
                    "mythril_tpu.ops.keccak", fromlist=["keccak256"]
                ).keccak256(m.group(1).encode()).hex()[:8]
                str_eval += str(
                    int(sign_hash, 16) in (self.disassembly.func_hashes if self.disassembly else [])
                )
                continue
            m = re.match(r"code#([a-zA-Z0-9\s,\[\]]+)#", token)
            if m:
                str_eval += str(m.group(1).strip() in self.code)
        return bool(eval(str_eval or "False"))  # noqa: S307 - mini-DSL, trusted input
