"""Instruction-semantics unit tests (reference parity: tests/instructions/).

Each test drives Instruction(op).evaluate on a hand-built GlobalState, the
same harness style the reference uses (e.g. tests/instructions/create_test.py).
"""

import pytest

from mythril_tpu.core.evm_exceptions import WriteProtection
from mythril_tpu.core.instructions import Instruction
from mythril_tpu.core.state.calldata import ConcreteCalldata
from mythril_tpu.core.state.world_state import WorldState
from mythril_tpu.core.transaction.transaction_models import (
    MessageCallTransaction,
    TransactionEndSignal,
)
from mythril_tpu.frontend.disassembler import Disassembly
from mythril_tpu.smt import symbol_factory

M = (1 << 256) - 1


def val(v, w=256):
    return symbol_factory.BitVecVal(v, w)


def make_state(code_hex="00", calldata=None, static=False):
    ws = WorldState()
    acct = ws.create_account(balance=0, address=0xAFFE, code=Disassembly(bytes.fromhex(code_hex)))
    tx = MessageCallTransaction(
        world_state=ws,
        callee_account=acct,
        caller=val(0xDEADBEEF),
        call_data=ConcreteCalldata("1", calldata or []),
        static=static,
    )
    gs = tx.initial_global_state()
    gs.transaction_stack.append((tx, None))
    return gs


def run_binop(op, a, b):
    gs = make_state()
    gs.mstate.stack.append(val(b))
    gs.mstate.stack.append(val(a))  # a on top: EVM pops a first
    (out,) = Instruction(op).evaluate(gs)
    return out.mstate.stack[-1].value


@pytest.mark.parametrize(
    "op,a,b,expected",
    [
        ("ADD", 2, 3, 5),
        ("ADD", M, 1, 0),
        ("SUB", 5, 7, M - 1),
        ("MUL", 1 << 128, 1 << 128, 0),
        ("DIV", 7, 2, 3),
        ("DIV", 7, 0, 0),
        ("SDIV", (-7) & M, 2, (-3) & M),
        ("MOD", 7, 3, 1),
        ("SMOD", (-7) & M, 3, (-1) & M),
        ("EXP", 2, 10, 1024),
        ("EXP", 3, 0, 1),
        ("LT", 1, 2, 1),
        ("LT", 2, 1, 0),
        ("GT", 2, 1, 1),
        ("SLT", M, 0, 1),  # -1 < 0 signed
        ("SGT", 0, M, 1),
        ("EQ", 5, 5, 1),
        ("EQ", 5, 6, 0),
        ("AND", 0b1100, 0b1010, 0b1000),
        ("OR", 0b1100, 0b1010, 0b1110),
        ("XOR", 0b1100, 0b1010, 0b0110),
        ("BYTE", 31, 0xFF, 0xFF),
        ("BYTE", 0, 0xFF, 0),
        ("BYTE", 32, 0xFF, 0),
        ("SHL", 1, 1, 2),  # shift=1 (top), value=1
        ("SHR", 1, 4, 2),
        ("SAR", 1, (1 << 255), (0b11 << 254)),
    ],
)
def test_binary_ops(op, a, b, expected):
    assert run_binop(op, a, b) == expected


def test_addmod_mulmod():
    gs = make_state()
    for x in (5, 7, 3):  # m, b, a (a on top)
        gs.mstate.stack.append(val(x))
    (out,) = Instruction("ADDMOD").evaluate(gs)
    assert out.mstate.stack[-1].value == (3 + 7) % 5

    gs = make_state()
    for x in (5, 7, 3):
        gs.mstate.stack.append(val(x))
    (out,) = Instruction("MULMOD").evaluate(gs)
    assert out.mstate.stack[-1].value == (3 * 7) % 5


def test_signextend():
    gs = make_state()
    gs.mstate.stack.append(val(0xFF))
    gs.mstate.stack.append(val(0))  # byte index 0
    (out,) = Instruction("SIGNEXTEND").evaluate(gs)
    assert out.mstate.stack[-1].value == M  # 0xff sign-extended = -1


def test_iszero_not():
    gs = make_state()
    gs.mstate.stack.append(val(0))
    (out,) = Instruction("ISZERO").evaluate(gs)
    assert out.mstate.stack[-1].value == 1
    gs = make_state()
    gs.mstate.stack.append(val(0))
    (out,) = Instruction("NOT").evaluate(gs)
    assert out.mstate.stack[-1].value == M


def test_push_dup_swap_pop():
    gs = make_state(code_hex="6042")  # PUSH1 0x42
    (out,) = Instruction("PUSH1").evaluate(gs)
    assert out.mstate.stack[-1].value == 0x42
    assert out.mstate.pc == 1

    gs = make_state()
    gs.mstate.stack.append(val(1))
    gs.mstate.stack.append(val(2))
    (out,) = Instruction("DUP2").evaluate(gs)
    assert out.mstate.stack[-1].value == 1

    gs = make_state()
    gs.mstate.stack.append(val(1))
    gs.mstate.stack.append(val(2))
    (out,) = Instruction("SWAP1").evaluate(gs)
    assert out.mstate.stack[-1].value == 1
    assert out.mstate.stack[-2].value == 2


def test_mstore_mload_roundtrip():
    gs = make_state()
    gs.mstate.stack.append(val(0x1234))
    gs.mstate.stack.append(val(0x40))  # offset on top
    (out,) = Instruction("MSTORE").evaluate(gs)
    out.mstate.stack.append(val(0x40))
    (out2,) = Instruction("MLOAD").evaluate(out)
    assert out2.mstate.stack[-1].value == 0x1234


def test_sstore_sload_roundtrip():
    gs = make_state()
    gs.mstate.stack.append(val(99))
    gs.mstate.stack.append(val(1))
    (out,) = Instruction("SSTORE").evaluate(gs)
    out.mstate.stack.append(val(1))
    (out2,) = Instruction("SLOAD").evaluate(out)
    assert out2.mstate.stack[-1].value == 99


def test_sstore_static_write_protection():
    gs = make_state(static=True)
    gs.mstate.stack.append(val(99))
    gs.mstate.stack.append(val(1))
    with pytest.raises(WriteProtection):
        Instruction("SSTORE").evaluate(gs)


def test_calldataload_concrete():
    gs = make_state(calldata=[0xAB, 0x12, 0x58, 0x50])
    gs.mstate.stack.append(val(0))
    (out,) = Instruction("CALLDATALOAD").evaluate(gs)
    assert out.mstate.stack[-1].value == int.from_bytes(
        bytes([0xAB, 0x12, 0x58, 0x50]) + bytes(28), "big"
    )


def test_sha3_concrete():
    from mythril_tpu.ops.keccak import keccak256

    gs = make_state()
    gs.mstate.memory.write_word_at(val(0), val(7))
    gs.mstate.stack.append(val(32))  # length
    gs.mstate.stack.append(val(0))  # offset on top
    (out,) = Instruction("SHA3").evaluate(gs)
    expected = int.from_bytes(keccak256((7).to_bytes(32, "big")), "big")
    assert out.mstate.stack[-1].value == expected


def test_jumpi_forks_two_ways():
    # PUSH1 1(dead) ... JUMPDEST at addr 4: code 600157005b00 -> JUMPI target 1? craft:
    # 0: PUSH1 0x05, 2: PUSH1 <cond> ... simpler: hand-build state at a JUMPI
    code = "6006600157005b00"  # PUSH1 6, PUSH1 1, JUMPI, STOP, JUMPDEST@6, STOP
    gs = make_state(code_hex=code)
    sym = symbol_factory.BitVecSym("c", 256)
    gs.mstate.stack.append(sym)  # condition (symbolic)
    gs.mstate.stack.append(val(6))  # dest byte addr = 6 (the JUMPDEST)
    gs.mstate.pc = 2  # index of the JUMPI
    states = Instruction("JUMPI").evaluate(gs)
    assert len(states) == 2
    pcs = sorted(s.mstate.pc for s in states)
    # fall-through -> index 3 (STOP); taken -> index 4 (JUMPDEST at addr 6)
    assert pcs == [3, 4]


def test_stop_raises_end_signal():
    gs = make_state()
    with pytest.raises(TransactionEndSignal) as exc:
        Instruction("STOP").evaluate(gs)
    assert exc.value.revert is False


def test_revert_raises_end_signal():
    gs = make_state()
    gs.mstate.stack.append(val(0))
    gs.mstate.stack.append(val(0))
    with pytest.raises(TransactionEndSignal) as exc:
        Instruction("REVERT").evaluate(gs)
    assert exc.value.revert is True


def test_selfdestruct_moves_balance():
    gs = make_state()
    gs.world_state.balances[val(0xAFFE)] = val(1000)
    gs.mstate.stack.append(val(0xD00D))  # beneficiary
    with pytest.raises(TransactionEndSignal):
        Instruction("SELFDESTRUCT").evaluate(gs)
