"""Probe solver tests: directed seeding, models, array consistency.

Mirrors the role of the reference's tests/laser/smt/ suite (solver behavior is
validated through a sat/unsat oracle table, cf. tests/laser/keccak_tests.py:7-39).
"""

import pytest

from mythril_tpu.exceptions import UnsatError
from mythril_tpu.smt import (
    And, Concat, Extract, If, Not, Solver, Optimize, ULT, UGT, symbol_factory,
    SAT, UNSAT, UNKNOWN,
)
from mythril_tpu.smt import terms


def bv(name):
    return symbol_factory.BitVecSym(name, 256)


def val(v):
    return symbol_factory.BitVecVal(v, 256)


def test_trivial_sat_unsat():
    s = Solver()
    x = bv("x")
    s.add(x == val(5))
    assert s.check() == SAT
    assert s.model().eval(x) == 5

    s2 = Solver()
    s2.add(val(1) == val(2))
    assert s2.check() == UNSAT


def test_structural_contradiction():
    s = Solver()
    x = bv("x")
    s.add(x == val(5))
    s.add(Not(x == val(5)))
    status = s.check()
    # Probe cannot hit this; without CDCL it may only say unknown — both
    # answers are acceptable, SAT would be a bug.
    assert status in (UNSAT, UNKNOWN)


def test_directed_equality_through_add():
    s = Solver()
    x = bv("x")
    s.add(x + val(100) == val(142))
    assert s.check() == SAT
    assert s.model().eval(x) == 42


def test_directed_through_concat_selector():
    """The calldata-selector pattern: Concat of byte reads == constant."""
    cd = []
    for i in range(4):
        cd.append(symbol_factory.BitVecSym(f"cd_{i}", 8))
    sel = Concat(*cd)
    s = Solver()
    s.add(sel == symbol_factory.BitVecVal(0xCBF0B0C0, 32))
    assert s.check() == SAT
    m = s.model()
    assert m.eval(cd[0]) == 0xCB
    assert m.eval(cd[3]) == 0xC0


def test_inequality_boundary():
    s = Solver()
    x = bv("x")
    s.add(ULT(x, val(10)))
    s.add(UGT(x, val(7)))
    assert s.check() == SAT
    assert s.model().eval(x) in (8, 9)


def test_array_select_consistency():
    from mythril_tpu.smt import Array

    a = Array("calldata", 256, 8)
    r0 = a[val(0)]
    r1 = a[val(1)]
    s = Solver()
    s.add(r0 == symbol_factory.BitVecVal(0xAA, 8))
    s.add(r1 == symbol_factory.BitVecVal(0xBB, 8))
    assert s.check() == SAT
    m = s.model()
    assert m.eval(r0) == 0xAA
    assert m.eval(r1) == 0xBB
    # identical indices must see identical values
    r0b = a[val(0)]
    assert m.eval(r0b) == 0xAA


def test_keccak_concrete_in_model():
    """Constraints over keccak of a probe-assigned value evaluate exactly."""
    from mythril_tpu.smt import Keccak

    x = bv("x")
    h = Keccak(x)
    s = Solver()
    s.add(x == val(0))
    s.add(h == val(0x290DECD9548B62A8D60345A988386FC84BA6BC95484008F6362F93160EF3E563))
    assert s.check() == SAT


def test_optimize_minimize_exact():
    x = bv("x")
    o = Optimize()
    o.add(ULT(val(5), x))
    o.minimize(x)
    assert o.check() == SAT
    # CDCL-backed bound search proves the exact minimum
    assert o._model.eval(x) == 6


def test_optimize_minimize_stable_across_seeds():
    from mythril_tpu.smt.solver import ProbeConfig

    for seed in (1, 7, 1234):
        x = bv(f"xs{seed}")
        o = Optimize(ProbeConfig(rng_seed=seed))
        o.add(UGT(x, val(100)))
        o.add(ULT(x, val(1 << 64)))
        o.minimize(x)
        assert o.check() == SAT
        assert o._model.eval(x) == 101, f"seed {seed} not minimal"


def test_optimize_maximize_exact():
    x = bv("xmax")
    o = Optimize()
    o.add(ULT(x, val(77)))
    o.maximize(x)
    assert o.check() == SAT
    assert o._model.eval(x) == 76


def test_optimize_lexicographic():
    # minimize a first, then b under a's pinned optimum
    a, b = bv("lexa"), bv("lexb")
    o = Optimize()
    o.add(UGT(a + b, val(10)))
    o.add(ULT(a, val(4)))
    o.minimize(a)
    o.minimize(b)
    assert o.check() == SAT
    assert o._model.eval(a) == 0
    assert o._model.eval(b) == 11


def test_independence_merge_does_not_clobber_other_buckets():
    """Regression: tier-0.5 recycles FULL models validated against one
    bucket only; merging must take just that bucket's own variables, or a
    stale assignment for another bucket's variable clobbers its witness
    (observed as exploit models violating `caller == ATTACKER`)."""
    from mythril_tpu.smt.solver import solve_conjunction
    from mythril_tpu.smt.concrete_eval import evaluate

    s = bv("indep_sender")
    d = bv("indep_data")
    AFFE, DEAD = 0xAFFE, 0xDEAD
    # first query: full model with s=AFFE lands in the recent-model cache
    st1, m1 = solve_conjunction(
        [(s == val(AFFE)).raw, (d == val(7)).raw]
    )
    assert st1 == SAT
    # second query splits into {d==7} (replayable from the recent model,
    # which also carries s=AFFE) and {s==DEAD}
    conj = [(d == val(7)).raw, (s == val(DEAD)).raw]
    st2, m2 = solve_conjunction(conj)
    assert st2 == SAT
    vals = evaluate(conj, m2)
    assert all(vals[c] for c in conj), "merged model violates the conjunction"
    assert m2.scalars[s.raw] == DEAD


def test_overflow_predicates():
    from mythril_tpu.smt import BVAddNoOverflow, BVMulNoOverflow, BVSubNoUnderflow

    a = val((1 << 256) - 1)
    b = val(2)
    assert BVAddNoOverflow(a, b, False).is_false
    assert BVAddNoOverflow(val(1), val(2), False).is_true
    assert BVMulNoOverflow(val(1 << 200), val(1 << 100), False).is_false
    assert BVMulNoOverflow(val(10), val(10), False).is_true
    assert BVSubNoUnderflow(val(1), val(2), False).is_false
    assert BVSubNoUnderflow(val(2), val(1), False).is_true


def test_taint_annotations_propagate():
    x = bv("x")
    x.annotate("tainted")
    y = x + val(1)
    assert "tainted" in y.annotations
    z = If(y == val(3), y, val(0))
    assert "tainted" in z.annotations
