"""Scheduling-policy and long-poll invariants: tenant quotas, load
shedding, priority aging, and the cursor-based subscribe path — policy
mechanics against a bare AdmissionController (synthetic codehashes, no
analysis) plus one real end-to-end long-poll through the service."""

import time

import pytest

from mythril_tpu.service import (
    AnalysisOptions,
    AnalysisService,
    AdmissionRejected,
    SchedulerPolicy,
    ServiceConfig,
)
from mythril_tpu.service.admission import AdmissionController, Flight
from mythril_tpu.service.request import AnalysisRequest

OPTS = AnalysisOptions(transaction_count=1)
CLEAN_HEX = "0x60006000f3"


def _req(rid, codehash=None, tier="batch", tenant=None, age_s=0.0):
    return AnalysisRequest(
        request_id=rid,
        name=rid,
        code=b"\x00",
        codehash=codehash or ("0x" + rid.ljust(64, "0")),
        options=OPTS,
        tier=tier,
        tenant=tenant,
        submitted_at=time.time() - age_s,
    )


def _ctl(**policy):
    return AdmissionController(
        result_cache_size=8, policy=SchedulerPolicy(**policy)
    )


class TestTenantQuota:
    def test_over_quota_submission_is_rejected(self):
        ctl = _ctl(max_pending_per_tenant=2)
        ctl.submit(_req("a1", tenant="acme"))
        ctl.submit(_req("a2", tenant="acme"))
        with pytest.raises(AdmissionRejected) as exc:
            ctl.submit(_req("a3", tenant="acme"))
        assert exc.value.kind == "quota"
        assert ctl.depths()["service.queue_depth"] == 2

    def test_quota_is_per_tenant(self):
        ctl = _ctl(max_pending_per_tenant=1)
        ctl.submit(_req("a1", tenant="acme"))
        # a different tenant is not constrained by acme's quota
        _stream, deduped = ctl.submit(_req("b1", tenant="blake"))
        assert deduped is False

    def test_dedup_subscription_is_never_refused(self):
        # subscribing to an existing flight adds no load: it must not
        # count against (or be blocked by) the tenant quota
        ctl = _ctl(max_pending_per_tenant=1)
        ctl.submit(_req("a1", codehash="0x" + "cc" * 32, tenant="acme"))
        _stream, deduped = ctl.submit(
            _req("a2", codehash="0x" + "cc" * 32, tenant="acme")
        )
        assert deduped is True

    def test_quota_frees_as_flights_run(self):
        ctl = _ctl(max_pending_per_tenant=1)
        ctl.submit(_req("a1", tenant="acme"))
        ctl.next_batch(max_width=4)  # a1 now running, not pending
        _stream, deduped = ctl.submit(_req("a2", tenant="acme"))
        assert deduped is False


class TestLoadShed:
    def test_batch_tier_is_shed_at_depth(self):
        ctl = _ctl(shed_queue_depth=2)
        ctl.submit(_req("r1"))
        ctl.submit(_req("r2"))
        with pytest.raises(AdmissionRejected) as exc:
            ctl.submit(_req("r3"))
        assert exc.value.kind == "shed"

    def test_interactive_tier_is_exempt_from_shedding(self):
        ctl = _ctl(shed_queue_depth=2)
        ctl.submit(_req("r1"))
        ctl.submit(_req("r2"))
        _stream, deduped = ctl.submit(_req("r3", tier="interactive"))
        assert deduped is False
        assert ctl.depths()["service.queue_depth"] == 3


class TestPriorityAging:
    def test_aged_batch_flight_beats_fresh_interactive(self):
        # a batch flight past age_priority_s joins the interactive
        # class; within the class FIFO wins, and it is older
        ctl = _ctl(age_priority_s=5.0)
        ctl.submit(_req("old", age_s=30.0))
        ctl.submit(_req("now", tier="interactive"))
        batch = ctl.next_batch(max_width=1)
        assert [f.requests[0].request_id for f in batch] == ["old"]

    def test_fresh_batch_still_yields_to_interactive(self):
        ctl = _ctl(age_priority_s=3600.0)
        ctl.submit(_req("young"))
        ctl.submit(_req("urgent", tier="interactive"))
        batch = ctl.next_batch(max_width=1)
        assert [f.requests[0].request_id for f in batch] == ["urgent"]

    def test_hot_tenant_cannot_starve_interactive(self):
        # the starvation scenario: one tenant floods the queue; the
        # quota bounds what it can hold pending, and a later
        # interactive submission still jumps straight to the anchor
        ctl = _ctl(max_pending_per_tenant=4, age_priority_s=3600.0)
        admitted, rejected = 0, 0
        for i in range(50):
            try:
                ctl.submit(_req(f"hot{i:02d}", tenant="hot"))
                admitted += 1
            except AdmissionRejected:
                rejected += 1
        assert admitted == 4 and rejected == 46
        ctl.submit(_req("user1", tier="interactive", tenant="user"))
        batch = ctl.next_batch(max_width=2)
        assert batch[0].requests[0].request_id == "user1"


class TestFlightPoll:
    def _flight(self):
        return Flight(("0x" + "ee" * 32, OPTS.key()), _req("p1"))

    def test_cursor_walks_the_event_log(self):
        flight = self._flight()
        flight.emit("accepted", {"request_id": "p1"})
        events, cursor, closed = flight.poll(0)
        assert [k for k, _ in events] == ["accepted"]
        assert (cursor, closed) == (1, False)
        flight.emit("issue", {"swc_id": "106"})
        flight.emit("done", {"issues": []})
        events, cursor, closed = flight.poll(cursor)
        assert [k for k, _ in events] == ["issue", "done"]
        assert (cursor, closed) == (3, True)
        # polling past the end of a finished flight: empty and closed
        assert flight.poll(cursor) == ([], 3, True)

    def test_poll_blocks_until_event_or_timeout(self):
        import threading

        flight = self._flight()
        t0 = time.perf_counter()
        events, _cursor, _closed = flight.poll(0, wait_s=0.1)
        assert events == [] and time.perf_counter() - t0 >= 0.09

        timer = threading.Timer(0.05, flight.emit, ("done", {"issues": []}))
        timer.start()
        try:
            events, _cursor, closed = flight.poll(0, wait_s=5.0)
        finally:
            timer.cancel()
        assert [k for k, _ in events] == ["done"] and closed is True


class TestServiceLongPoll:
    def test_poll_replays_the_whole_stream(self, scoped_args):
        from tests.service.test_service_core import _config

        service = AnalysisService(_config(probe=False)).start()
        try:
            req, stream, _ = service.submit(CLEAN_HEX, name="lp")
            polled, cursor = [], 0
            deadline = time.time() + 120
            while time.time() < deadline:
                out = service.poll(req.request_id, cursor, wait_s=5.0)
                polled.extend(out["events"])
                cursor = out["cursor"]
                if out["closed"]:
                    break
            else:
                pytest.fail("long-poll never closed")
            streamed = list(stream.events(timeout=30))
            assert [k for k, _ in polled] == [k for k, _ in streamed]
            assert polled[-1][0] == "done"
        finally:
            service.stop(drain=True, timeout=30)

    def test_unknown_request_id_raises(self, scoped_args):
        from tests.service.test_service_core import _config

        service = AnalysisService(_config()).start()
        try:
            with pytest.raises(KeyError):
                service.poll("r999999")
        finally:
            service.stop(drain=False, timeout=10)


def test_config_builds_policy_only_when_armed():
    assert ServiceConfig().scheduler_policy() is None
    policy = ServiceConfig(tenant_quota=3, age_priority_s=10.0).scheduler_policy()
    assert policy is not None
    assert policy.max_pending_per_tenant == 3
    assert policy.age_priority_s == 10.0
