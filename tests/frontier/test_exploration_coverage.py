"""Device-harvested coverage planes vs the host engine's walk (PR-14).

``engine._merge_coverage`` folds the device frontier's ``[3, C, I]``
visited planes into the exploration ledger; the host engine with the
coverage plugin enabled is the oracle bitmap.  On a branching contract
the device run must cover every instruction the host run covers (device
coverage is speculative, so it may mark more — never less), and both
JUMPI edges of the explored dispatcher gate must be present in the edge
planes.
"""

import pytest

from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.observability.exploration import get_exploration_ledger
from mythril_tpu.observability.metrics import get_registry
from mythril_tpu.support.support_args import args as global_args
from mythril_tpu.support.support_utils import get_code_hash

# dispatcher prelude: selector(kill()=0x41c0e1b5) -> JUMPDEST at 0x14=20,
# then an unprotected SELFDESTRUCT — two reachable branches of one JUMPI
DISPATCH = "60003560e01c6341c0e1b5146014576000" + "6000fd" + "5b"
CODE_HEX = DISPATCH + "33ff"


def _run(frontier: bool):
    """One symbolic execution; returns the ledger's bitmap snapshot for
    the contract (reset before the run so the snapshot is this run's)."""
    get_registry().reset()
    led = get_exploration_ledger()
    led.reset_scope()
    saved = (global_args.frontier, global_args.frontier_force)
    global_args.frontier = frontier
    global_args.frontier_force = frontier
    try:
        SymExecWrapper(
            bytes.fromhex(CODE_HEX),
            address=0x0901D12E,
            strategy="dfs",
            transaction_count=1,
            execution_timeout=60,
            modules=["AccidentallyKillable"],
            enable_coverage_strategy=not frontier,
        )
    finally:
        global_args.frontier, global_args.frontier_force = saved
    snap = led.snapshot()
    codehash = get_code_hash(CODE_HEX)
    return snap, snap["bitmaps"].get(codehash)


@pytest.mark.slow
def test_device_planes_agree_with_host_walk():
    host_snap, host = _run(frontier=False)
    dev_snap, dev = _run(frontier=True)
    assert host is not None, "host coverage plugin never fed the ledger"
    assert dev is not None, "device merge never fed the ledger"

    host_instr = set(host["instr"])
    dev_instr = set(dev["instr"])
    assert host_instr, "host run covered nothing"
    # device coverage is speculative (UNSAT forks mark before rollback):
    # it may exceed the host bitmap but must never miss what the host
    # actually executed
    missing = host_instr - dev_instr
    assert not missing, (
        f"device planes missed host-executed instructions {sorted(missing)}"
    )

    # both branch edges of the dispatcher JUMPI were explored (the
    # selector match jumps to the JUMPDEST, the mismatch falls through
    # to the revert) — the edge planes must show both
    assert dev["edge_taken"], "no taken JUMPI edge recorded"
    assert dev["edge_fall"], "no fall-through JUMPI edge recorded"

    # the jsonv2 surface for the same run
    cov = dev_snap["coverage"][get_code_hash(CODE_HEX)]
    assert cov["instruction_pct"] > 0
    assert cov["edges_seen"] >= 2


def test_frontier_terminations_are_classified():
    _run(frontier=True)
    # the run above reset the registry then analyzed on-device: whatever
    # terminated must partition exactly across the eight classes
    led = get_exploration_ledger()
    term = led.terminated()
    assert sum(term.values()) == led.terminated_total()
    assert led.terminated_total() > 0, "no path termination was stamped"
    assert term["completed"] > 0
