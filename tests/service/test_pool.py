"""Worker-pool end-to-end tests: digest parity with the solo path,
crash containment, drain-on-stop with busy workers, and the
cross-process completed-result store.

Each pool test spawns real worker processes (spawn start method, so a
fresh interpreter imports the engine); contracts are tiny and warmup is
off to keep the module inside the tier-1 budget."""

import os
import signal
import time
from pathlib import Path

import pytest

from mythril_tpu.service import (
    AnalysisOptions,
    AnalysisService,
    ServiceConfig,
    issue_digest,
)

REPO = Path(__file__).resolve().parents[2]
KILL_SIMPLE_HEX = (
    REPO / "tests" / "testdata" / "inputs" / "kill_simple.bin-runtime"
).read_text().strip()
CLEAN_HEX = "0x60006000f3"

OPTS = AnalysisOptions(transaction_count=1, execution_timeout=30)


def _config(**overrides):
    base = dict(
        default_options=OPTS,
        max_batch_width=1,  # one flight per job: fan out across workers
        batch_window_s=0.05,
        frontier=False,
        probe=False,
        warmup=False,
    )
    base.update(overrides)
    return ServiceConfig(**base)


def _digests(service, code, name):
    _req, stream, _ = service.submit(code, name=name)
    summary = stream.result(timeout=180)
    return sorted(issue_digest(i) for i in summary["issues"])


def _restarts_now():
    from mythril_tpu.observability.metrics import get_registry

    return get_registry().counter(
        "service.worker_restarts", persistent=True
    ).snapshot() or 0


def test_pool_digests_bit_identical_to_solo(scoped_args):
    solo = AnalysisService(_config()).start()
    try:
        want = {
            "kill": _digests(solo, KILL_SIMPLE_HEX, "kill"),
            "clean": _digests(solo, CLEAN_HEX, "clean"),
        }
    finally:
        assert solo.stop(drain=True, timeout=30) is True
    assert want["kill"] and not want["clean"]

    pool = AnalysisService(_config(workers=2)).start()
    try:
        assert pool.wait_warm(timeout=600) is True
        assert pool.pooled is True
        got = {
            "kill": _digests(pool, KILL_SIMPLE_HEX, "kill"),
            "clean": _digests(pool, CLEAN_HEX, "clean"),
        }
        stats = pool.stats()
        assert len(stats["workers"]) == 2
    finally:
        assert pool.stop(drain=True, timeout=60) is True
    assert got == want


def test_worker_crash_errors_only_its_requests(scoped_args):
    r0 = _restarts_now()
    # two transactions widen the execution window so the kill lands
    # while the victim batch is genuinely in flight
    slow = AnalysisOptions(transaction_count=2, execution_timeout=60)
    service = AnalysisService(_config(workers=2)).start()
    try:
        assert service.wait_warm(timeout=600) is True
        _req, victim, _ = service.submit(
            KILL_SIMPLE_HEX, name="victim", options=slow
        )
        # wait for dispatch, then kill that worker process outright
        deadline = time.time() + 60
        pid = None
        while time.time() < deadline:
            busy = [w for w in service.worker_stats()
                    if w["state"] == "busy"]
            if busy:
                pid = busy[0]["pid"]
                break
            time.sleep(0.01)
        assert pid is not None, "victim batch was never dispatched"
        os.kill(pid, signal.SIGKILL)

        events = list(victim.events(timeout=120))
        kinds = [k for k, _ in events]
        # the dead worker's request errors — no silent requeue, so no
        # done event and no issues from a half-run analysis
        assert kinds[-1] == "error"
        assert "died" in events[-1][1]
        assert "done" not in kinds

        # the daemon survives: a follow-up request completes normally
        # on the remaining/respawned workers
        _req2, stream2, _ = service.submit(CLEAN_HEX, name="after")
        assert stream2.result(timeout=180)["issues"] == []
        assert _restarts_now() >= r0 + 1
        assert service.stats()["service.worker_restarts"] >= 1
    finally:
        service.stop(drain=True, timeout=60)


def test_stop_drains_busy_workers(scoped_args):
    service = AnalysisService(_config(workers=2)).start()
    try:
        assert service.wait_warm(timeout=600) is True
        _r1, s1, _ = service.submit(KILL_SIMPLE_HEX, name="d1")
        _r2, s2, _ = service.submit(CLEAN_HEX, name="d2")
    finally:
        # SIGTERM path: drain must let in-flight work finish, not drop it
        assert service.stop(drain=True, timeout=180) is True
    kill_summary = s1.result(timeout=10)
    assert [i["swc_id"] for i in kill_summary["issues"]] == ["106"]
    assert s2.result(timeout=10)["issues"] == []


def test_result_store_replays_across_processes(scoped_args, tmp_path):
    from mythril_tpu.observability.metrics import get_registry
    from mythril_tpu.service.resultstore import ResultStore

    reg = get_registry()
    hits0 = reg.counter(
        "service.result_store_hits", persistent=True
    ).snapshot() or 0
    cache_root = str(tmp_path / "cache")

    first = AnalysisService(_config(cache_root=cache_root)).start()
    try:
        want = _digests(first, KILL_SIMPLE_HEX, "kill")
    finally:
        assert first.stop(drain=True, timeout=30) is True

    # the completed-result store persisted the terminal event log
    store = ResultStore(os.path.join(cache_root, "results"))
    assert len(store) == 1

    # a FRESH daemon over the same cache root replays without analysis:
    # this is the cross-worker/cross-process dedup hit
    second = AnalysisService(_config(cache_root=cache_root)).start()
    try:
        req, stream, deduped = second.submit(KILL_SIMPLE_HEX, name="again")
        assert deduped is True
        summary = stream.result(timeout=10)
        assert sorted(issue_digest(i) for i in summary["issues"]) == want
        hits1 = reg.counter(
            "service.result_store_hits", persistent=True
        ).snapshot() or 0
        assert hits1 >= hits0 + 1
    finally:
        assert second.stop(drain=True, timeout=30) is True


def test_result_store_keeps_only_done_logs(tmp_path):
    from mythril_tpu.service.resultstore import ResultStore

    store = ResultStore(str(tmp_path / "results"))
    key = ("0x" + "ab" * 32, OPTS.key())
    store.put(key, [("issue", {"swc_id": "106"}), ("error", "boom")])
    assert store.get(key) is None  # not a completed result
    done = [("issue", {"swc_id": "106"}), ("done", {"issues": []})]
    store.put(key, done)
    assert store.get(key) == done
    # unknown key misses cleanly
    assert store.get(("0x" + "cd" * 32, OPTS.key())) is None
