"""UncheckedRetval: call return value never checked before tx end (SWC-104).

Reference parity: mythril/analysis/module/modules/unchecked_retval.py:1-141.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import UNCHECKED_RET_VAL
from mythril_tpu.core.state.annotation import StateAnnotation
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.smt import symbol_factory

DESCRIPTION = """
Test whether CALL return value is checked.
For direct calls, the Solidity compiler auto-generates this check. E.g.:
    Alice c = Alice(address);
    c.ping(42);
Here the CALL will be followed by IZSERO(retval).
For low-level-calls this check is omitted. E.g.:
    c.call.value(0)(bytes4(sha3("ping(uint256)")),1);
"""


class RetvalAnnotation(StateAnnotation):
    def __init__(self):
        self.retvals: List[Dict] = []

    def __copy__(self):
        out = RetvalAnnotation()
        out.retvals = [dict(r) for r in self.retvals]
        return out


class UncheckedRetval(DetectionModule):
    name = "Return value of an external call is not checked"
    swc_id = UNCHECKED_RET_VAL
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["STOP", "RETURN"]
    # staticpass: STOP/RETURN only check retvals recorded by the call
    # post-hooks, so no call-family op means no possible issue
    static_required_ops = frozenset({"CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"})
    post_hooks = ["CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"]

    def _execute(self, state: GlobalState) -> Optional[List[Issue]]:
        if self._cache_key(state) in self.cache:
            return None
        return self._analyze_state(state)

    def _analyze_state(self, state: GlobalState) -> List[Issue]:
        instruction = state.get_current_instruction()
        annotations = state.get_annotations(RetvalAnnotation)
        if not annotations:
            annotation = RetvalAnnotation()
            state.annotate(annotation)
        else:
            annotation = annotations[0]

        if instruction["opcode"] in ("STOP", "RETURN"):
            issues = []
            for retval in annotation.retvals:
                try:
                    # the tx can end successfully even when the call failed
                    transaction_sequence = get_transaction_sequence(
                        state,
                        state.world_state.constraints
                        + [retval["retval"] == symbol_factory.BitVecVal(0, 256)],
                    )
                except UnsatError:
                    continue
                issues.append(
                    Issue(
                        contract=state.environment.active_account.contract_name,
                        function_name=state.node.function_name if state.node else "unknown",
                        address=retval["address"],
                        swc_id=UNCHECKED_RET_VAL,
                        title="Unchecked return value from external call.",
                        severity="Medium",
                        bytecode=state.environment.code.bytecode,
                        description_head="The return value of a message call is not checked.",
                        description_tail=(
                            "External calls return a boolean value. If the callee "
                            "halts with an exception, 'false' is returned and "
                            "execution continues in the caller. The caller should "
                            "check whether an exception happened and react "
                            "accordingly to avoid unexpected behavior. For example "
                            "it is often desirable to wrap external calls in "
                            "require() so the transaction is reverted if the call "
                            "fails."
                        ),
                        gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
                        transaction_sequence=transaction_sequence,
                    )
                )
            return issues

        # post-CALL: remember the pushed return-value symbol
        if state.mstate.stack:
            retval = state.mstate.stack[-1]
            if retval.value is None:
                annotation.retvals.append(
                    {"address": state.instruction["address"] - 1, "retval": retval}
                )
        return []


detector = UncheckedRetval
