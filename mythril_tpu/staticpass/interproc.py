"""Interprocedural value-set refinement over the static CFG.

The base :class:`~mythril_tpu.staticpass.cfg.StaticCFG` resolves jump
targets only from constants pushed *within the same block*; anything
else fans out to every JUMPDEST.  This module runs a bounded fixpoint
of a value-set abstract interpreter over the whole frame instead:

* abstract value = ``None`` (unknown, ⊤) or a ``frozenset`` of at most
  :data:`VSET_CAP` concrete 256-bit values,
* abstract stack = list of abstract values tracked from the frame base
  (an EVM frame always enters at pc 0 with an empty stack, so heights
  are absolute),
* join = per-position value union (⊤ on overflow), with stacks of
  unequal height aligned from the top and truncated to the shorter one,
* transfer = PUSH/PC/DUP/SWAP/POP plus the constant folds solc's
  optimizer output needs (arithmetic, shifts, comparisons, ISZERO/NOT);
  every other opcode pops its arity and pushes ⊤.

The lattice is finite and the transfer monotone, so the fixpoint
terminates; a visit budget additionally bounds the worst case, and
exhaustion returns ``None`` so the caller falls back to the base CFG
(strictly coarser, never wrong).

The converged result is a :class:`RefinedFlow` that duck-types
``StaticCFG`` (``underflow_points`` and ``may_reach`` run on it
unchanged) but with *refined* successor lists: a JUMP whose destination
value-set is known gets edges only to those destinations, and a JUMPI
whose condition folds to all-zero / never-zero loses its taken / fall
edge.  Refinement only ever REMOVES edges relative to the base CFG —
the over-approximation contract every consumer relies on — and
``summarize`` double-checks that with an explicit reachability-subset
invariant before trusting the refinement.
"""

from __future__ import annotations

from typing import Callable, FrozenSet, List, Optional, Tuple

import numpy as np

from mythril_tpu.staticpass.cfg import (
    _FOLD_BINOPS,
    _U256,
    E_DYN,
    E_FALL,
    E_JUMP,
    StaticCFG,
)

# abstract value: None = unknown (⊤), else a frozenset of concrete values
AbsVal = Optional[FrozenSet[int]]
AbsStack = List[AbsVal]

VSET_CAP = 8  # widest value-set before widening to ⊤
_STACK_CAP = 48  # deepest tracked stack; deeper entries are forgotten (⊤)
_VISIT_BUDGET_PER_BLOCK = 24
_VISIT_BUDGET_MIN = 512

# folds beyond cfg._FOLD_BINOPS that dispatch ladders and guard code use;
# same convention: first lambda arg is the value popped first (stack top)
_CMP_BINOPS = {
    "EQ": lambda a, b: 1 if a == b else 0,
    "LT": lambda a, b: 1 if a < b else 0,
    "GT": lambda a, b: 1 if a > b else 0,
    "DIV": lambda a, b: a // b if b else 0,
    "MOD": lambda a, b: a % b if b else 0,
}
_UNOPS = {
    "ISZERO": lambda a: 1 if a == 0 else 0,
    "NOT": lambda a: (~a) & _U256,
}


def _join_val(a: AbsVal, b: AbsVal) -> AbsVal:
    if a is None or b is None:
        return None
    if a == b:
        return a
    u = a | b
    return u if len(u) <= VSET_CAP else None


def _join_stack(old: Optional[AbsStack], new: AbsStack) -> Tuple[AbsStack, bool]:
    """Join ``new`` into ``old`` (None = not yet visited); returns the
    joined stack and whether it differs from ``old``."""
    if old is None:
        return list(new), True
    h = min(len(old), len(new))
    out: AbsStack = []
    changed = len(old) != h
    for j in range(h):
        v = _join_val(old[len(old) - h + j], new[len(new) - h + j])
        out.append(v)
        if v != old[len(old) - h + j]:
            changed = True
    return out, changed


def _peek(stk: AbsStack, k: int) -> AbsVal:
    """k-th value from the top (k=1 is top); ⊤ past the tracked region."""
    return stk[-k] if len(stk) >= k else None


def _fold2(name: str, va: AbsVal, vb: AbsVal) -> AbsVal:
    if va is None or vb is None:
        return None
    fn = _FOLD_BINOPS.get(name) or _CMP_BINOPS[name]
    out = set()
    for a in va:
        for b in vb:
            out.add(fn(a, b) & _U256)
            if len(out) > VSET_CAP:
                return None
    return frozenset(out)


def _step(t, i: int, stk: AbsStack) -> None:
    """Apply instruction ``i``'s transfer to ``stk`` in place."""
    name = t.names[i]
    if name.startswith("PUSH"):
        stk.append(frozenset({(t.arg[i] or 0) & _U256}))
    elif name == "PC":
        stk.append(frozenset({int(t.addr[i])}))
    elif name.startswith("DUP"):
        k = int(name[3:])
        stk.append(stk[-k] if len(stk) >= k else None)
    elif name.startswith("SWAP"):
        k = int(name[4:])
        if len(stk) < k + 1:
            stk[:0] = [None] * (k + 1 - len(stk))
        stk[-1], stk[-k - 1] = stk[-k - 1], stk[-1]
    elif name == "POP":
        if stk:
            stk.pop()
    elif name in _UNOPS:
        a = stk.pop() if stk else None
        stk.append(
            frozenset(_UNOPS[name](x) for x in a) if a is not None else None
        )
    elif name in _FOLD_BINOPS or name in _CMP_BINOPS:
        a = stk.pop() if stk else None
        b = stk.pop() if stk else None
        stk.append(_fold2(name, a, b))
    else:
        for _ in range(int(t.arity[i])):
            if stk:
                stk.pop()
        stk.extend([None] * int(t.pushes[i]))
    if len(stk) > _STACK_CAP:
        del stk[: len(stk) - _STACK_CAP]


def walk_block(
    tables,
    entry_stack: AbsStack,
    start: int,
    end: int,
    observer: Optional[Callable[[int, AbsStack], None]] = None,
) -> AbsStack:
    """Run the abstract transfer over instrs [start, end); ``observer``
    sees (instr_index, stack_before_instr) for each one."""
    stk = list(entry_stack)
    for i in range(start, end):
        if observer is not None:
            observer(i, stk)
        _step(tables, i, stk)
    return stk


def _jump_dest_blocks(flow, dest: AbsVal) -> Tuple[List[int], bool]:
    """(successor block ids, is_dyn_fan).  ⊤ destination keeps the base
    over-approximation (every JUMPDEST); constant members resolve to
    their JUMPDEST block or — if invalid — to nothing (the VM halts)."""
    t = flow.tables
    if dest is None:
        return list(dict.fromkeys(flow.jumpdest_blocks)), True
    out = []
    for d in dest:
        j = t.jumpdest_at_addr.get(int(d))
        if j is not None:
            out.append(int(flow.block_id[j]))
    return list(dict.fromkeys(out)), False


def _taken_dead(cond: AbsVal) -> bool:
    return cond is not None and all(c == 0 for c in cond)


def _fall_dead(cond: AbsVal) -> bool:
    return cond is not None and 0 not in cond


class RefinedFlow:
    """Refined CFG view: same blocks as the base :class:`StaticCFG`, but
    successor lists / static targets recomputed from converged value
    sets, plus the per-block entry stacks for downstream site capture
    (function summaries, call-site folding).  Duck-types ``StaticCFG``
    for ``underflow_points`` and ``may_reach``."""

    def __init__(self, cfg: StaticCFG, entry_stacks: List[Optional[AbsStack]]):
        self.tables = cfg.tables
        self.n_blocks = cfg.n_blocks
        self.block_start = cfg.block_start
        self.block_end = cfg.block_end
        self.block_id = cfg.block_id
        self.jumpdest_blocks = cfg.jumpdest_blocks
        self.entry_stacks = entry_stacks
        n = cfg.tables.n
        self.static_target = np.full(n, -1, np.int32)
        self.n_resolved = 0
        self.succ: List[List[int]] = [[] for _ in range(self.n_blocks)]
        self.succ_kind: List[List[str]] = [[] for _ in range(self.n_blocks)]
        self._build()

    def entry_stack(self, b: int) -> AbsStack:
        """Converged entry stack for block ``b``; an empty stack (every
        peek past it reads ⊤) when the fixpoint never reached it."""
        stk = self.entry_stacks[b] if 0 <= b < len(self.entry_stacks) else None
        return stk if stk is not None else []

    def _resolve_singleton(self, last: int, dest: AbsVal) -> None:
        if dest is not None and len(dest) == 1:
            j = self.tables.jumpdest_at_addr.get(int(next(iter(dest))))
            if j is not None:
                self.static_target[last] = j
                self.n_resolved += 1

    def _add(self, b: int, to: int, kind: str) -> None:
        self.succ[b].append(to)
        self.succ_kind[b].append(kind)

    def _build(self) -> None:
        t = self.tables
        for b in range(self.n_blocks):
            if self.entry_stacks[b] is None:
                continue  # never reached during the fixpoint
            s, e = int(self.block_start[b]), int(self.block_end[b])
            stk = walk_block(t, self.entry_stacks[b], s, e - 1)
            last = e - 1
            fall = b + 1 if b + 1 < self.n_blocks else None
            if t.is_terminator[last]:
                continue
            if t.is_jump[last]:
                dest = _peek(stk, 1)
                self._resolve_singleton(last, dest)
                dests, dyn = _jump_dest_blocks(self, dest)
                for d in dests:
                    self._add(b, d, E_DYN if dyn else E_JUMP)
            elif t.is_jumpi[last]:
                dest, cond = _peek(stk, 1), _peek(stk, 2)
                if not _taken_dead(cond):
                    self._resolve_singleton(last, dest)
                    dests, dyn = _jump_dest_blocks(self, dest)
                    for d in dests:
                        self._add(b, d, E_DYN if dyn else E_JUMP)
                if not _fall_dead(cond) and fall is not None:
                    self._add(b, fall, E_FALL)
            elif fall is not None:
                self._add(b, fall, E_FALL)

    # duck-typed StaticCFG surface
    def reachable_blocks(self, halting: Optional[np.ndarray] = None) -> np.ndarray:
        return StaticCFG.reachable_blocks(self, halting)

    def edge_list(self) -> List[Tuple[int, int, str]]:
        return StaticCFG.edge_list(self)


def refine(cfg: StaticCFG) -> Optional[RefinedFlow]:
    """Run the value-set fixpoint; None when the budget is exhausted
    (the caller keeps the base CFG — coarser but still sound)."""
    B = cfg.n_blocks
    if not B:
        return None
    t = cfg.tables
    budget = max(_VISIT_BUDGET_MIN, _VISIT_BUDGET_PER_BLOCK * B)
    entry: List[Optional[AbsStack]] = [None] * B
    entry[0] = []  # a frame always enters at pc 0 with an empty stack
    work = [0]
    inwork = [False] * B
    inwork[0] = True
    visits = 0
    while work:
        b = work.pop()
        inwork[b] = False
        visits += 1
        if visits > budget:
            return None
        s, e = int(cfg.block_start[b]), int(cfg.block_end[b])
        stk = walk_block(t, entry[b], s, e - 1)
        last = e - 1
        succs: List[int] = []
        if not t.is_terminator[last]:
            fall = b + 1 if b + 1 < B else None
            if t.is_jump[last]:
                succs, _ = _jump_dest_blocks(cfg, _peek(stk, 1))
            elif t.is_jumpi[last]:
                dest, cond = _peek(stk, 1), _peek(stk, 2)
                if not _taken_dead(cond):
                    succs, _ = _jump_dest_blocks(cfg, dest)
                    succs = list(succs)
                if not _fall_dead(cond) and fall is not None:
                    succs.append(fall)
            elif fall is not None:
                succs = [fall]
        if succs:
            _step(t, last, stk)  # exit stack (same for every successor)
            for nb in succs:
                joined, changed = _join_stack(entry[nb], stk)
                if changed:
                    entry[nb] = joined
                    if not inwork[nb]:
                        inwork[nb] = True
                        work.append(nb)
    return RefinedFlow(cfg, entry)
