"""fire_lasers: run POST modules and collect all issues.

Reference parity: mythril/analysis/security.py:28-45.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from mythril_tpu.analysis.module.base import EntryPoint
from mythril_tpu.analysis.module.loader import ModuleLoader
from mythril_tpu.analysis.report import Issue
from mythril_tpu.observability import tracer as _otrace

log = logging.getLogger(__name__)


def retrieve_callback_issues(white_list: Optional[List[str]] = None) -> List[Issue]:
    issues: List[Issue] = []
    for module in ModuleLoader().get_detection_modules(
        entry_point=EntryPoint.CALLBACK, white_list=white_list
    ):
        issues.extend(module.issues)
    reset_callback_modules(module_names=white_list)
    return issues


def fire_lasers(statespace, white_list: Optional[List[str]] = None) -> List[Issue]:
    log.info("Starting analysis")
    issues: List[Issue] = []
    with _otrace.span("analysis.post_modules", cat="analysis"):
        for module in ModuleLoader().get_detection_modules(
            entry_point=EntryPoint.POST, white_list=white_list
        ):
            log.info("Executing %s", module.name)
            result = module.execute(statespace)
            if result:
                issues.extend(result)
    issues.extend(retrieve_callback_issues(white_list))
    return issues


def reset_callback_modules(module_names: Optional[List[str]] = None) -> None:
    for module in ModuleLoader().get_detection_modules(
        entry_point=EntryPoint.CALLBACK, white_list=module_names
    ):
        module.reset_module()
