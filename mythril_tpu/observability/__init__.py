"""Unified tracing & metrics for the analyzer runtime.

Two pieces, both process-wide singletons:

* :mod:`mythril_tpu.observability.tracer` — a low-overhead span tracer
  (context-manager / decorator API over a thread-safe ring buffer) with
  Chrome-trace/Perfetto JSON and flat JSONL exporters.  Disabled by
  default; when disabled every instrumentation site costs one attribute
  check and returns a shared no-op context manager.

* :mod:`mythril_tpu.observability.metrics` — a registry of named
  counters / gauges / histograms that absorbs the mutable-attribute
  telemetry style of ``FrontierStatistics`` and ``SolverStatistics``.
  Those classes remain as thin facades over the registry so existing
  call sites and report-meta output are unchanged.

The convenience re-exports below are the recommended import surface::

    from mythril_tpu.observability import get_tracer, get_registry, span

    with span("frontier.segment", cat="frontier", k=64):
        ...
"""

from mythril_tpu.observability.deviceplane import (  # noqa: F401
    bucket_tag,
    device_meta,
    dispatch_scope,
    install_deviceplane,
)
from mythril_tpu.observability.drift import (  # noqa: F401
    diff_history_windows,
    diff_tables,
    format_drift,
)
from mythril_tpu.observability.exploration import (  # noqa: F401
    TERM_CLASSES,
    ExplorationLedger,
    exploration_meta,
    get_exploration_ledger,
)
from mythril_tpu.observability.fleet import (  # noqa: F401
    WIRE_VERSION,
    FleetAggregator,
    FleetPublisher,
)
from mythril_tpu.observability.flightrecorder import (  # noqa: F401
    FlightRecorder,
    arm_flight_recorder,
    build_bundle,
    disarm_flight_recorder,
    get_flight_recorder,
    register_dump_listener,
    register_flight_context,
    unregister_dump_listener,
    unregister_flight_context,
)
from mythril_tpu.observability.heartbeat import (  # noqa: F401
    HeartbeatSampler,
    get_heartbeat,
)
from mythril_tpu.observability.history import (  # noqa: F401
    HistoryReader,
    MetricsHistory,
)
from mythril_tpu.observability.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    LabeledCounter,
    MetricsRegistry,
    get_registry,
    prometheus_text,
)
from mythril_tpu.observability.tracer import (  # noqa: F401
    Tracer,
    device_annotation,
    get_tracer,
    span,
    traced,
)
from mythril_tpu.observability.watchtower import (  # noqa: F401
    Objective,
    Watchtower,
    default_objectives,
    get_watchtower,
    health_meta,
    load_slo_file,
    set_watchtower,
)


def observability_meta() -> dict:
    """Snapshot block embedded in report meta and BENCH rows."""
    # Materialize the facade-backed metrics first so the snapshot always
    # carries the full frontier.*/solver.* key set, even for runs where a
    # stage never executed (e.g. narrow workloads that bail off-device).
    from mythril_tpu.frontier.stats import FrontierStatistics
    from mythril_tpu.querycache.cache import materialize_counters
    from mythril_tpu.smt.solver import SolverStatistics

    FrontierStatistics()._materialize()
    SolverStatistics()
    materialize_counters()
    tracer = get_tracer()
    meta = {"metrics": get_registry().snapshot()}
    if tracer.enabled or len(tracer):
        meta["trace"] = tracer.summary()
    return meta


def reset_analysis_metrics() -> None:
    """Reset per-analysis telemetry at the start of an analysis.

    Clears every non-persistent metric in the registry (which resets the
    ``FrontierStatistics`` / ``SolverStatistics`` facades with it).
    Metrics registered with ``persistent=True`` — e.g. the frontier's
    per-code slow/narrow-segment verdicts, which engine.py deliberately
    keeps across runs so a code that degenerated once is not re-probed —
    survive the sweep.  The exploration ledger's coverage bitmaps are
    swept with the same scope (its counters live in the registry and
    reset with everything else).
    """
    get_registry().reset()
    get_exploration_ledger().reset_scope()
    # the adaptive controller's plan cache / coverage history / latched
    # coverage-stop verdict all describe the scope being swept
    from mythril_tpu.adaptive import get_adaptive_controller

    get_adaptive_controller().reset_scope()
