"""Minimal JSON-RPC client for chain reads (geth/Infura compatible).

Reference parity: mythril/ethereum/interface/rpc/client.py:30+ — eth_getCode,
eth_getStorageAt, eth_getBalance, eth_getTransactionByHash &c.  Network access
is gated: in a zero-egress environment every call raises RPCError, which the
DynLoader treats as "unknown account".
"""

from __future__ import annotations

import json
from typing import Optional
from urllib import request as _urlreq


class RPCError(Exception):
    pass


class EthJsonRpc:
    def __init__(self, host: str = "localhost", port: int = 8545, tls: bool = False):
        self.host = host
        self.port = port
        self.tls = tls
        self._id = 0

    @property
    def endpoint(self) -> str:
        scheme = "https" if self.tls else "http"
        if self.host.startswith("http"):
            return self.host
        return f"{scheme}://{self.host}:{self.port}"

    def _call(self, method: str, params=None):
        self._id += 1
        payload = {
            "jsonrpc": "2.0",
            "method": method,
            "params": params or [],
            "id": self._id,
        }
        req = _urlreq.Request(
            self.endpoint,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with _urlreq.urlopen(req, timeout=10) as resp:
                data = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 - every transport failure is an RPCError
            raise RPCError(f"RPC request to {self.endpoint} failed: {e}") from e
        if "error" in data and data["error"]:
            raise RPCError(str(data["error"]))
        return data.get("result")

    def eth_getCode(self, address: str, default_block: str = "latest") -> str:
        return self._call("eth_getCode", [address, default_block])

    def eth_getStorageAt(
        self, address: str, position: int, default_block: str = "latest"
    ) -> str:
        return self._call("eth_getStorageAt", [address, hex(position), default_block])

    def _call_int(self, method: str, params=None) -> int:
        result = self._call(method, params)
        return int(result, 16) if result else 0

    def eth_getBalance(self, address: str, default_block: str = "latest") -> int:
        return self._call_int("eth_getBalance", [address, default_block])

    def eth_getTransactionByHash(self, tx_hash: str):
        return self._call("eth_getTransactionByHash", [tx_hash])

    def eth_getTransactionReceipt(self, tx_hash: str):
        return self._call("eth_getTransactionReceipt", [tx_hash])

    def eth_blockNumber(self) -> int:
        return int(self._call("eth_blockNumber"), 16)

    def eth_coinbase(self) -> str:
        return self._call("eth_coinbase")

    def eth_getBlockByNumber(self, block="latest", tx_objects: bool = True):
        if isinstance(block, int):
            block = hex(block)
        return self._call("eth_getBlockByNumber", [block, tx_objects])

    def eth_getTransactionCount(self, address: str, default_block: str = "latest") -> int:
        return self._call_int("eth_getTransactionCount", [address, default_block])

    def eth_call(self, to: str, data: str, default_block: str = "latest") -> str:
        return self._call("eth_call", [{"to": to, "data": data}, default_block])

    def close(self) -> None:
        """API parity with the reference client; urllib holds no connection."""
