"""Concolic driver: replay a concrete input, then flip requested branches.

Reference parity: mythril/concolic/concolic_execution.py:22-85.
"""

from __future__ import annotations

import binascii
import json
from datetime import datetime, timedelta
from typing import Dict, List, Optional

from mythril_tpu.concolic.concrete_data import ConcreteData
from mythril_tpu.concolic.find_trace import concrete_execution, setup_concrete_initial_state
from mythril_tpu.core.strategy.concolic import ConcolicStrategy
from mythril_tpu.core.svm import LaserEVM
from mythril_tpu.core.transaction import symbolic as sym_tx
from mythril_tpu.core.transaction.transaction_models import tx_id_manager


def flip_branches(
    init_state, concrete_data: ConcreteData, jump_addresses: List[int],
    trace: List, hits_out: Optional[Dict] = None,
) -> List[Dict]:
    """Re-execute symbolically along the trace, flipping requested JUMPIs.

    ``hits_out`` (when given) is filled with addr → bool(result): which
    requested flips actually produced a new concrete input — the adaptive
    flip counters read it; output parity is untouched."""
    tx_id_manager.restart_counter()
    output_list = []
    laser_evm = LaserEVM(
        execution_timeout=600,
        transaction_count=len(concrete_data["steps"]),
        requires_statespace=False,
    )
    laser_evm.open_states = [init_state]
    laser_evm.strategy = ConcolicStrategy(
        work_list=laser_evm.work_list,
        max_depth=128,
        trace=trace,
        flip_branch_addresses=jump_addresses,
    )

    from mythril_tpu.support.time_handler import time_handler

    time_handler.start_execution(laser_evm.execution_timeout)

    for transaction in concrete_data["steps"]:
        sym_tx.execute_message_call(
            laser_evm, int(transaction["address"], 16)
        )

    if isinstance(laser_evm.strategy, ConcolicStrategy):
        for addr, result in laser_evm.strategy.results.items():
            if hits_out is not None:
                hits_out[addr] = bool(result)
            if result:
                output_list.append(result)
    return output_list


def concolic_execution(
    concrete_data: ConcreteData,
    jump_addresses: List[int],
    solver_timeout: int = 100000,
    flip_targets: Optional[List[int]] = None,
) -> List[Dict]:
    """Main entry (reference :67-85): returns new concrete inputs, one per
    flipped branch.

    ``flip_targets`` are PLANNED flips from the adaptive controller —
    uncovered-JUMPI addrs the steering plan ranked by static
    interesting-point priority.  They merge into ``jump_addresses``
    (dedup, caller order first so explicitly requested flips keep their
    precedence) and their outcomes feed the ``adaptive.flips_planned`` /
    ``adaptive.flips_hit`` counters: a planned addr whose flip produced a
    new concrete input is a hit."""
    from mythril_tpu.support.support_args import args
    from mythril_tpu.support.time_handler import time_handler

    planned = [a for a in (flip_targets or []) if a not in set(jump_addresses)]
    if planned:
        jump_addresses = list(jump_addresses) + planned
    old_timeout = args.solver_timeout
    old_remaining = time_handler.time_remaining()
    args.solver_timeout = solver_timeout
    # (concrete_execution and flip_branches each reset the process-global
    # time budget themselves; this frame only restores the caller's)
    try:
        init_state, trace = concrete_execution(concrete_data)
        hits: Dict = {}
        out = flip_branches(init_state, concrete_data, jump_addresses,
                            trace, hits_out=hits)
        if planned:
            _count_planned_flips(planned, hits)
        return out
    finally:
        # leaked process-global budgets silently reshape every later
        # analysis (solver_timeout feeds the engine's prune/confirm
        # deadlines; the time handler feeds every exec loop)
        args.solver_timeout = old_timeout
        time_handler.start_execution(max(0, old_remaining))


def _count_planned_flips(planned: List[int], hits: Dict) -> None:
    """Feed the adaptive flip counters; telemetry only, never raises."""
    try:
        from mythril_tpu.adaptive import get_adaptive_controller

        get_adaptive_controller().count_flips(
            planned=len(planned),
            hit=sum(1 for a in planned if hits.get(a)),
        )
    except Exception:
        pass
