"""Differential tests for the cooperative corpus driver.

The sequential per-contract analysis (the reference's corpus scheduling,
mythril/mythril/mythril_analyzer.py:138-175) is the oracle: running the same
contracts cooperatively — lockstep tx rounds, one multi-code frontier batch
per round — must find the same issues per contract.
"""

import pathlib

import pytest

from mythril_tpu.analysis.cooperative import analyze_cooperative
from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.support.support_args import args as global_args

CORPUS = pathlib.Path("/root/reference/tests/testdata/inputs")

# distinct detectors, distinct codes: exercises multi-code batching for real
FIXTURES = {
    "suicide.sol.o": "106",
    "origin.sol.o": "115",
    "exceptions.sol.o": "110",
    "overflow.sol.o": "101",
}

# Determinism: the fixtures are small enough that exploration EXHAUSTS the
# state space well inside this ceiling (the timeout is a never-hit guard,
# not a horizon), and the solver budget is generous enough that every
# confirmation that can land does land — so both schedulings see identical
# state sets and identical verdicts on every rep, machine load aside.
EXPLORATION_CEILING_S = 300
SOLVER_BUDGET_MS = 30_000


def _clear():
    from mythril_tpu.analysis.module.loader import ModuleLoader

    reset_callback_modules()
    for m in ModuleLoader().get_detection_modules():
        if hasattr(m, "cache"):
            m.cache.clear()


def _jobs():
    if not CORPUS.is_dir():
        pytest.skip("reference corpus not mounted")
    jobs = []
    for name in FIXTURES:
        code = bytes.fromhex(
            (CORPUS / name).read_text().strip().replace("0x", "")
        )
        jobs.append((name, code))
    return jobs


def _sequential(jobs):
    out = {}
    for name, code in jobs:
        _clear()
        sym = SymExecWrapper(
            code,
            address=0x0901D12E,
            strategy="bfs",
            transaction_count=2,
            execution_timeout=EXPLORATION_CEILING_S,
        )
        out[name] = fire_lasers(sym)
    return out


def keys(issues):
    return sorted((i.swc_id, i.address, i.function) for i in issues)


def _run_both(jobs, frontier):
    old_budget = global_args.solver_timeout
    global_args.solver_timeout = SOLVER_BUDGET_MS
    try:
        sequential = _sequential(jobs)
        _clear()
        old = (global_args.frontier, global_args.frontier_force)
        global_args.frontier = frontier
        global_args.frontier_force = frontier
        try:
            cooperative, total_states = analyze_cooperative(
                jobs,
                transaction_count=2,
                execution_timeout=EXPLORATION_CEILING_S,
            )
        finally:
            global_args.frontier, global_args.frontier_force = old
    finally:
        global_args.solver_timeout = old_budget
    assert total_states > 0
    return cooperative, sequential


@pytest.mark.parametrize("frontier", [False, True])
def test_cooperative_matches_sequential(frontier):
    jobs = _jobs()
    cooperative, sequential = _run_both(jobs, frontier)
    for name, swc in FIXTURES.items():
        assert keys(cooperative[name]) == keys(sequential[name]), (
            f"{name}: cooperative={keys(cooperative[name])} "
            f"sequential={keys(sequential[name])}"
        )
        assert any(i.swc_id == swc for i in cooperative[name])
