"""Reachable-edge oracle in the exploration ledger: the corrected
coverage denominator (`coverage_pct_reachable`) and its defensive
guarantee — reachable coverage can never dip below raw coverage, even
with misaligned or missing static masks."""

import numpy as np
import pytest

from mythril_tpu.observability.exploration import ExplorationLedger
from mythril_tpu.observability.metrics import MetricsRegistry


def _ledger():
    return ExplorationLedger(registry=MetricsRegistry())


def _mask(total, live):
    m = np.zeros(total, bool)
    m[list(live)] = True
    return m


def test_reachable_denominator_lifts_coverage():
    led = _ledger()
    # 10 decoded instructions, only the first 5 statically reachable,
    # all 5 of those executed: raw 50%, reachable 100%
    led.record_instr("h", 10, range(5))
    led.register_static("h", _mask(10, range(5)), _mask(10, []), _mask(10, []))
    assert led.coverage_pct("h") == 50.0
    assert led.coverage_pct_reachable("h") == 100.0
    d = led.coverage()["h"]
    assert d["instruction_pct_raw"] == 50.0
    assert d["instruction_pct_reachable"] == 100.0
    assert d["instructions_reachable"] == 5


def test_without_masks_reachable_equals_raw():
    led = _ledger()
    led.record_instr("h", 10, range(3))
    assert led.coverage_pct_reachable("h") == led.coverage_pct("h") == 30.0
    d = led.coverage()["h"]
    assert d["instruction_pct_reachable"] == d["instruction_pct_raw"]
    assert d["instructions_reachable"] is None


def test_executed_bits_union_into_reach_mask():
    led = _ledger()
    # an instruction OUTSIDE the static mask executed (mask is wrong or
    # misaligned): it is unioned into the denominator, so reachable
    # coverage still cannot exceed 100 or dip below raw
    led.record_instr("h", 10, [7])
    led.register_static("h", _mask(10, range(5)), _mask(10, []), _mask(10, []))
    d = led.coverage()["h"]
    assert d["instructions_reachable"] == 6  # 5 static + the stray bit
    assert d["instruction_pct_reachable"] >= d["instruction_pct_raw"]
    assert d["instruction_pct_reachable"] <= 100.0


def test_mask_longer_than_code_is_truncated():
    led = _ledger()
    led.record_instr("h", 4, [0, 1])
    led.register_static("h", _mask(8, range(8)), _mask(8, []), _mask(8, []))
    d = led.coverage()["h"]
    assert d["instructions_total"] == 4
    assert d["instructions_reachable"] == 4
    assert d["instruction_pct_reachable"] == 50.0


def test_mask_shorter_than_code_is_padded():
    led = _ledger()
    led.record_instr("h", 8, [0, 1])
    led.register_static("h", _mask(2, range(2)), _mask(2, []), _mask(2, []))
    d = led.coverage()["h"]
    assert d["instructions_total"] == 8
    assert d["instructions_reachable"] == 2
    assert d["instruction_pct_reachable"] == 100.0


def test_aggregate_mixes_masked_and_unmasked_codes():
    led = _ledger()
    led.record_instr("a", 10, range(5))
    led.register_static("a", _mask(10, range(5)), _mask(10, []), _mask(10, []))
    led.record_instr("b", 10, range(5))  # no masks: raw denominator
    assert led.coverage_pct() == 50.0
    # aggregate: (5+5) executed over (5 reachable + 10 raw) = 66.67
    assert led.coverage_pct_reachable() == pytest.approx(66.67, abs=0.01)
    assert led.coverage_pct_reachable() >= led.coverage_pct()


def test_edge_denominator_uses_reachable_masks():
    led = _ledger()
    planes = np.zeros((3, 8), bool)
    planes[0, :4] = True  # instr
    planes[1, 2] = True  # taken at the first JUMPI
    led.record_device_planes("h", 8, 2, planes)
    d = led.coverage()["h"]
    assert d["edges_total"] == 4  # 2 JUMPIs, raw denominator
    assert d["edge_pct_raw"] == 25.0
    # statically only one JUMPI's two edges are reachable
    led.register_static(
        "h", _mask(8, range(8)), _mask(8, [2]), _mask(8, [2])
    )
    d = led.coverage()["h"]
    assert d["edges_reachable"] == 2
    assert d["edge_pct_reachable"] == 50.0
    assert d["edge_pct_reachable"] >= d["edge_pct_raw"]


def test_reset_scope_drops_masks_too():
    led = _ledger()
    led.record_instr("h", 4, [0])
    led.register_static("h", _mask(4, range(4)), _mask(4, []), _mask(4, []))
    led.reset_scope()
    assert led.coverage() == {}
    assert led.coverage_pct_reachable("h") is None
