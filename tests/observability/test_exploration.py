"""Exploration ledger: coverage bitmaps, termination attribution, solver
hotspots — and the coverage-plugin pc-clamp regression (PR-14).
"""

import numpy as np
import pytest

from mythril_tpu.observability.exploration import (
    _MAX_HOTSPOT_LABELS,
    ExplorationLedger,
    TERM_CLASSES,
    VERDICT_CLASS,
    get_exploration_ledger,
)
from mythril_tpu.observability.metrics import MetricsRegistry


def _ledger():
    return ExplorationLedger(registry=MetricsRegistry())


# ---------------------------------------------------------------------------
# termination attribution
# ---------------------------------------------------------------------------


def test_stamp_partitions_exactly():
    led = _ledger()
    led.stamp("completed", 3)
    led.stamp("solver_unsat")
    led.stamp("prefilter_killed", 2)
    term = led.terminated()
    assert term["completed"] == 3
    assert term["solver_unsat"] == 1
    assert term["prefilter_killed"] == 2
    assert sum(term.values()) == led.terminated_total() == 6
    assert led.meta()["partition_ok"]


def test_stamp_rejects_unknown_class():
    with pytest.raises(ValueError):
        _ledger().stamp("fell_off_a_cliff")


def test_every_class_is_stampable():
    led = _ledger()
    for cls in TERM_CLASSES:
        led.stamp(cls)
    assert led.terminated_total() == len(TERM_CLASSES)
    assert all(n == 1 for n in led.terminated().values())


def test_verdict_class_maps_into_taxonomy():
    assert set(VERDICT_CLASS.values()) <= set(TERM_CLASSES)
    assert VERDICT_CLASS["unsat"] == "solver_unsat"
    assert VERDICT_CLASS["unknown"] == "solver_timeout_unknown"
    assert VERDICT_CLASS["prefilter"] == "prefilter_killed"


# ---------------------------------------------------------------------------
# coverage bitmaps
# ---------------------------------------------------------------------------


def test_device_planes_fold_and_pct():
    led = _ledger()
    planes = np.zeros((3, 10), bool)
    planes[0, [0, 1, 2, 5]] = True  # 4/10 instructions
    planes[1, 2] = True  # taken edge at the JUMPI
    planes[2, 2] = True  # fall-through edge
    led.record_device_planes("0xabc", 10, 1, planes)
    cov = led.coverage()["0xabc"]
    assert cov["instructions_seen"] == 4
    assert cov["instruction_pct"] == 40.0
    assert cov["edges_total"] == 2
    assert cov["edges_seen"] == 2
    assert cov["edge_pct"] == 100.0
    assert led.coverage_pct("0xabc") == 40.0


def test_device_planes_union_is_cumulative():
    led = _ledger()
    a = np.zeros((3, 4), bool)
    a[0, 0] = True
    b = np.zeros((3, 4), bool)
    b[0, 3] = True
    led.record_device_planes("0xabc", 4, 0, a)
    led.record_device_planes("0xabc", 4, 0, b)
    assert led.coverage()["0xabc"]["instructions_seen"] == 2


def test_aggregate_coverage_weighted_by_size():
    led = _ledger()
    led.record_instr("0xbig", 100, range(50))  # 50%
    led.record_instr("0xsmall", 10, range(10))  # 100%
    # (50 + 10) / (100 + 10)
    assert led.coverage_pct() == pytest.approx(54.55, abs=0.01)


def test_record_instr_oob_counts_overflow_not_clamp():
    led = _ledger()
    led.record_instr("0xabc", 4, [0, 3, 4, 99])
    cov = led.coverage()["0xabc"]
    assert cov["instructions_seen"] == 2, "OOB indices must not mark"
    assert led.pc_overflow == 2
    assert led.meta()["pc_overflow"] == 2


def test_coverage_gauge_published_per_codehash():
    reg = MetricsRegistry()
    led = ExplorationLedger(registry=reg)
    led.record_instr("0x" + "ab" * 20, 4, [0, 1])
    value = reg.gauge("exploration.coverage_pct", default={}).snapshot()
    assert value == {("0x" + "ab" * 20)[:10]: 50.0}


def test_snapshot_bitmaps_are_index_lists():
    led = _ledger()
    planes = np.zeros((3, 6), bool)
    planes[0, [1, 4]] = True
    planes[1, 4] = True
    led.record_device_planes("0xabc", 6, 1, planes)
    snap = led.snapshot()
    assert snap["bitmaps"]["0xabc"]["instr"] == [1, 4]
    assert snap["bitmaps"]["0xabc"]["edge_taken"] == [4]
    assert snap["bitmaps"]["0xabc"]["edge_fall"] == []


def test_reset_scope_clears_bitmaps_only():
    led = _ledger()
    led.record_instr("0xabc", 4, [0])
    led.stamp("completed")
    led.reset_scope()
    assert led.coverage() == {}
    # registry counters are swept by reset_analysis_metrics, not here
    assert led.terminated_total() == 1


# ---------------------------------------------------------------------------
# solver hotspots
# ---------------------------------------------------------------------------


def test_solver_hotspots_ranked_by_time():
    led = _ledger()
    led.record_solver_time("0xaaaa:0x14", 0.5)
    led.record_solver_time("0xaaaa:0x14", 0.25)
    led.record_solver_time("0xbbbb:0x20", 0.1)
    top = led.solver_hotspots(top=2)
    assert top[0]["point"] == "0xaaaa:0x14"
    assert top[0]["solver_s"] == 0.75
    assert top[0]["queries"] == 2
    assert top[1]["point"] == "0xbbbb:0x20"


def test_solver_hotspot_cardinality_cap():
    led = _ledger()
    for i in range(_MAX_HOTSPOT_LABELS + 10):
        led.record_solver_time(f"0xc:{i:#x}", 0.001)
    secs = led._reg().labeled_counter(
        "exploration.solver_hotspot_s", label_name="point"
    )
    assert len(secs) <= _MAX_HOTSPOT_LABELS + 1  # distinct labels + "other"
    assert "other" in secs


# ---------------------------------------------------------------------------
# process singleton + meta shape
# ---------------------------------------------------------------------------


def test_exploration_meta_shape():
    from mythril_tpu.observability import exploration_meta

    assert get_exploration_ledger() is get_exploration_ledger()
    meta = exploration_meta()
    assert set(meta) == {
        "coverage_pct", "coverage_pct_raw", "coverage_pct_reachable",
        "coverage", "terminated", "terminated_total",
        "partition_ok", "solver_hotspots", "pc_overflow",
    }
    assert set(meta["terminated"]) == set(TERM_CLASSES)


# ---------------------------------------------------------------------------
# coverage-plugin pc clamp regression (the OOB pc used to be clamped onto
# the LAST instruction, silently inflating its coverage)
# ---------------------------------------------------------------------------


class _StubVM:
    def __init__(self):
        self.hooks = {}

    def register_laser_hooks(self, kind, hook):
        self.hooks[kind] = hook


class _StubCode:
    def __init__(self, n):
        self.bytecode = bytes(range(n))
        self.instruction_list = [object()] * n


class _StubState:
    def __init__(self, code, pc):
        import types

        self.environment = types.SimpleNamespace(code=code)
        self.mstate = types.SimpleNamespace(pc=pc)


def _fresh_scoped_registry():
    from mythril_tpu.observability.metrics import get_registry

    get_registry().reset(prefix="exploration.")
    return get_registry()


def test_plugin_oob_pc_counts_overflow_instead_of_clamping():
    from mythril_tpu.plugins.plugins.coverage import InstructionCoverage

    reg = _fresh_scoped_registry()
    plugin = InstructionCoverage()
    vm = _StubVM()
    plugin.initialize(vm)
    code = _StubCode(4)
    vm.hooks["execute_state"](_StubState(code, 1))
    vm.hooks["execute_state"](_StubState(code, 9))  # OOB: off the end
    seen = plugin.coverage[code.bytecode.hex()][1]
    assert seen[1] and not seen[3], "OOB pc must not mark the last instr"
    assert reg.counter("exploration.pc_overflow").value == 1


def test_record_visited_oob_counts_overflow():
    from mythril_tpu.plugins.plugins.coverage import InstructionCoverage

    reg = _fresh_scoped_registry()
    plugin = InstructionCoverage()
    plugin.record_visited("aabb", 4, [0, 2, 7, 8])
    assert plugin.coverage["aabb"][1] == [True, False, True, False]
    assert reg.counter("exploration.pc_overflow").value == 2


def test_coverage_strategy_oob_state_is_not_covered():
    from mythril_tpu.plugins.plugins.coverage import (
        CoverageStrategy,
        InstructionCoverage,
    )

    plugin = InstructionCoverage()
    code = _StubCode(4)
    plugin.coverage[code.bytecode.hex()] = (4, [True, True, True, True])
    strategy = CoverageStrategy.__new__(CoverageStrategy)
    strategy.coverage_plugin = plugin
    assert strategy._is_covered(_StubState(code, 2))
    assert not strategy._is_covered(_StubState(code, 9)), (
        "an OOB pc never executed, so it must not read as covered"
    )


def test_stop_hook_publishes_coverage_gauge():
    from mythril_tpu.plugins.plugins.coverage import InstructionCoverage
    from mythril_tpu.support.support_utils import get_code_hash

    reg = _fresh_scoped_registry()
    get_exploration_ledger().reset_scope()
    plugin = InstructionCoverage()
    vm = _StubVM()
    plugin.initialize(vm)
    code = _StubCode(4)
    vm.hooks["execute_state"](_StubState(code, 0))
    vm.hooks["execute_state"](_StubState(code, 2))
    vm.hooks["stop_sym_exec"]()
    gauge = reg.gauge("exploration.coverage_pct", default={}).snapshot()
    key = get_code_hash(code.bytecode.hex())[:10]
    assert gauge.get(key) == 50.0


# ---------------------------------------------------------------------------
# WorkerContext.exploration_delta (service accounting seam)
# ---------------------------------------------------------------------------


def test_exploration_delta_measures_scope():
    from mythril_tpu.facade.warm import WorkerContext
    from mythril_tpu.observability.metrics import get_registry

    get_registry().reset(prefix="exploration.")
    led = get_exploration_ledger()
    led.reset_scope()
    led.stamp("completed", 5)  # pre-existing: must not land in the delta
    ctx = WorkerContext.__new__(WorkerContext)
    out = {}
    with ctx.exploration_delta(out):
        led.stamp("completed", 2)
        led.stamp("loop_bound")
        led.record_instr("0xddd", 10, range(4))
        led.record_pc_overflow(3)
    assert out["terminated"] == {"completed": 2, "loop_bound": 1}
    assert out["terminated_total"] == 3
    assert out["pc_overflow"] == 3
    assert out["coverage_pct"]["0xddd"] == 40.0
