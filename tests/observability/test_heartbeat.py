"""Heartbeat sampler: sources, gauges, counter tracks, JSONL, error budget."""

import json
import time

import pytest

from mythril_tpu.observability.heartbeat import HeartbeatSampler, get_heartbeat
from mythril_tpu.observability.metrics import get_registry
from mythril_tpu.observability.tracer import get_tracer


@pytest.fixture
def hb():
    s = HeartbeatSampler(period_s=0.01)
    yield s
    s.reset()


def test_sample_now_sets_gauges_and_tail(hb):
    reg = get_registry()
    hb.register("pipe", lambda: {
        "test.hb.depth": 7,
        "test.hb.free_slots_by_shard": {"shard0": 3, "shard1": 5},
    })
    sample = hb.sample_now()
    assert sample["test.hb.depth"] == 7
    # scalar and per-shard dict values both land as gauges
    assert reg.gauge("test.hb.depth").value == 7
    assert reg.gauge("test.hb.free_slots_by_shard").value == {
        "shard0": 3, "shard1": 5,
    }
    (tail,) = hb.recent_samples()
    assert tail["tick"] == 1 and tail["test.hb.depth"] == 7
    reg.reset(prefix="test.hb.")


def test_counter_events_on_heartbeat_track(hb):
    tracer = get_tracer()
    tracer.reset()
    tracer.enabled = True
    try:
        hb.register("pipe", lambda: {
            "test.hb.depth": 2,
            "test.hb.by_shard": {"shard0": 1},
        })
        hb.sample_now()
        counters = [s for s in tracer.spans() if s.get("ph") == "C"]
        assert {c["name"] for c in counters} == {
            "test.hb.depth", "test.hb.by_shard",
        }
        # all counter samples ride one named synthetic track
        (tid,) = {c["tid"] for c in counters}
        assert tracer.thread_names()[tid] == "heartbeat"
    finally:
        tracer.enabled = False
        tracer.reset()
        get_registry().reset(prefix="test.hb.")


def test_daemon_thread_ticks_and_writes_jsonl(hb, tmp_path):
    out = tmp_path / "heartbeat.jsonl"
    hb.register("pipe", lambda: {"test.hb.live": 1})
    hb.start(period_s=0.01, out_path=str(out))
    assert hb.running
    deadline = time.time() + 5.0
    while hb.ticks < 3 and time.time() < deadline:
        time.sleep(0.01)
    hb.stop()
    assert not hb.running
    assert hb.ticks >= 3
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) >= 3
    assert all(l["test.hb.live"] == 1 for l in lines)
    # ticks are monotonically numbered and stamped
    assert [l["tick"] for l in lines] == sorted(l["tick"] for l in lines)
    assert all("t" in l for l in lines)
    get_registry().reset(prefix="test.hb.")


def test_source_error_budget_tolerates_transient_races(hb):
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:  # two transient failures, then healthy
            raise RuntimeError("racing the pipeline")
        return {"test.hb.flaky": calls["n"]}

    hb.register("flaky", flaky)
    assert hb.sample_now() == {}
    assert hb.sample_now() == {}
    # under the MAX_SOURCE_ERRORS budget: the source is retried and recovers
    assert hb.sample_now()["test.hb.flaky"] == 3
    get_registry().reset(prefix="test.hb.")


def test_source_dropped_after_consecutive_error_budget(hb):
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise RuntimeError("permanently broken")

    hb.register("broken", broken)
    for _ in range(HeartbeatSampler.MAX_SOURCE_ERRORS + 3):
        assert hb.sample_now() == {}
    # dropped after the budget: no further calls
    assert calls["n"] == HeartbeatSampler.MAX_SOURCE_ERRORS
    # re-registering resets the budget
    hb.register("broken", lambda: {"test.hb.fixed": 1})
    assert hb.sample_now()["test.hb.fixed"] == 1
    get_registry().reset(prefix="test.hb.")


def test_unregister_and_reset(hb):
    hb.register("a", lambda: {"test.hb.a": 1})
    hb.unregister("a")
    assert hb.sample_now() == {}
    hb.register("b", lambda: {"test.hb.b": 1})
    hb.sample_now()
    hb.reset()
    assert hb.recent_samples() == [] and hb.ticks == 0
    assert hb.sample_now() == {}  # sources forgotten too
    get_registry().reset(prefix="test.hb.")


def test_singleton_accessor():
    assert get_heartbeat() is get_heartbeat()


def test_source_failures_are_counted_and_visible(hb):
    reg = get_registry()
    reg.reset(include_persistent=True, prefix="heartbeat.")

    def broken():
        raise RuntimeError("permanently broken")

    hb.register("broken", broken)
    for _ in range(HeartbeatSampler.MAX_SOURCE_ERRORS + 2):
        hb.sample_now()
    errors = dict(reg.labeled_counter(
        "heartbeat.source_errors", persistent=True))
    # every failed sample counted, attributed to the source by name
    assert errors["broken"] == HeartbeatSampler.MAX_SOURCE_ERRORS
    # the drop itself counted exactly once
    assert reg.counter(
        "heartbeat.sources_dropped", persistent=True).value == 1
    assert hb.dropped_sources() == ["broken"]
    assert hb.source_error_counts()["broken"] >= \
        HeartbeatSampler.MAX_SOURCE_ERRORS
    # re-registering clears the dropped state
    hb.register("broken", lambda: {"test.hb.ok": 1})
    hb.sample_now()
    assert hb.dropped_sources() == []
    reg.reset(include_persistent=True, prefix="heartbeat.")
    reg.reset(prefix="test.hb.")
