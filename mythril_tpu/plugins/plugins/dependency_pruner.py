"""Dependency pruner: skip blocks that can't touch storage written earlier.

Reference parity: mythril/laser/plugin/plugins/dependency_pruner.py:142-318 —
builds a cross-transaction map of storage locations read per basic block; in
transaction N >= 2, a path is skipped when the blocks it is about to execute
cannot read any location written by the previous transactions.  Symbolic
locations are handled the way the reference does (:142-195): a read/write
pair counts as a potential dependency iff ``read == write`` is satisfiable —
checked here as ONE batched feasibility sweep over all pairs (the same
batched-prune kernel the engine uses) instead of one Z3 call per pair.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Set

from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.plugins.interface import LaserPlugin, PluginBuilder
from mythril_tpu.plugins.plugin_annotations import (
    DependencyAnnotation,
    WSDependencyAnnotation,
)
from mythril_tpu.plugins.signals import PluginSkipState
from mythril_tpu.smt import terms as T

log = logging.getLogger(__name__)


def _loc_key(index):
    """Storage location as stored in the dependency maps: a concrete int for
    constants, the raw interned term for symbolic indices."""
    return index.value if index.value is not None else index.raw


def _as_term(loc):
    return T.const(loc, 256) if isinstance(loc, int) else loc


def _key_of(loc):
    return (0, loc) if isinstance(loc, int) else (1, loc.tid)


def may_intersect(reads: Set, written: Set, cache: Dict = None) -> bool:
    """Could any read location equal any written location?

    Fast paths: identical interned terms / equal ints.  A location term
    recorded during transaction N captures THAT transaction's symbolic
    inputs (e.g. ``1_calldata``); a later transaction re-derives the same
    expression over fresh inputs, so when two terms SHARE variables an
    UNSAT on ``r == w`` proves nothing about future instances — such pairs
    always count as potential dependencies.  Variable-disjoint pairs are
    decided by satisfiability of ``r == w``: one batched sweep first
    (reference dependency_pruner.py:169-195 solves each pair with Z3), then
    an exact-UNSAT confirmation for the survivors, because the batch treats
    UNKNOWN as unsat and pruning must explore on uncertainty.  Verdicts
    memoize in ``cache`` (symmetric keys) across the run."""
    if not reads or not written:
        return False
    if reads & written:  # interned terms: identity covers symbolic equality
        return True

    undecided = []  # (key, eq term)
    for r in reads:
        for w in written:
            key = tuple(sorted((_key_of(r), _key_of(w))))
            verdict = cache.get(key) if cache is not None else None
            if verdict is True:
                return True
            if verdict is False:
                continue
            if isinstance(r, int) and isinstance(w, int):
                if cache is not None:
                    cache[key] = r == w
                if r == w:
                    return True
                continue
            rt, wt = _as_term(r), _as_term(w)
            if set(T.free_vars([rt])) & set(T.free_vars([wt])):
                if cache is not None:
                    cache[key] = True
                return True
            undecided.append((key, T.eq(rt, wt)))
    if not undecided:
        return False

    from mythril_tpu.smt.solver import UNSAT, check_satisfiable_batch, solve_conjunction

    flags = check_satisfiable_batch([[eq] for _k, eq in undecided])
    hit = False
    for (key, eq), sat in zip(undecided, flags):
        if sat:
            if cache is not None:
                cache[key] = True
            hit = True
    if hit:
        return True
    for key, eq in undecided:
        status, _ = solve_conjunction([eq])
        if status != UNSAT:
            # uncertainty: explore (do not cache — a later budget may decide)
            return True
        if cache is not None:
            cache[key] = False
    return False


def get_dependency_annotation(state: GlobalState) -> DependencyAnnotation:
    annotations = state.get_annotations(DependencyAnnotation)
    if annotations:
        return annotations[0]
    # inherit from the world state's annotation stack if present
    ws_annotations = state.world_state.get_annotations(WSDependencyAnnotation)
    if ws_annotations and ws_annotations[0].annotations_stack:
        annotation = ws_annotations[0].annotations_stack[-1].__copy__()
    else:
        annotation = DependencyAnnotation()
    state.annotate(annotation)
    return annotation


def get_ws_dependency_annotation(state: GlobalState) -> WSDependencyAnnotation:
    ws_annotations = state.world_state.get_annotations(WSDependencyAnnotation)
    if ws_annotations:
        return ws_annotations[0]
    annotation = WSDependencyAnnotation()
    state.world_state.annotate(annotation)
    return annotation


class DependencyPruner(LaserPlugin):
    def __init__(self):
        self.sloads_on_path: Dict[int, Set] = {}
        self.iteration = 0
        self._pair_cache: Dict = {}

    def initialize(self, symbolic_vm) -> None:
        self.iteration = 0

        def start_sym_trans_hook():
            self.iteration += 1

        def sload_hook(global_state: GlobalState):
            annotation = get_dependency_annotation(global_state)
            key = _loc_key(global_state.mstate.stack[-1])
            annotation.storage_loaded.add(key)
            for block in annotation.path:
                self.sloads_on_path.setdefault(block, set()).add(key)

        def sstore_hook(global_state: GlobalState):
            annotation = get_dependency_annotation(global_state)
            key = _loc_key(global_state.mstate.stack[-1])
            annotation.extend_storage_write_cache(self.iteration, key)

        def call_hook(global_state: GlobalState):
            annotation = get_dependency_annotation(global_state)
            annotation.has_call = True

        def jump_hook(global_state: GlobalState):
            annotation = get_dependency_annotation(global_state)
            address = global_state.get_current_instruction()["address"]
            annotation.path.append(address)
            if self.iteration < 2:
                return
            if annotation.has_call:
                return
            # would this block possibly read something written before?
            written = set()
            for it in range(self.iteration):
                written |= annotation.storage_written.get(it, set())
            ws_annotation = get_ws_dependency_annotation(global_state)
            for dep in ws_annotation.annotations_stack:
                for it, keys in dep.storage_written.items():
                    written |= keys
            reads = self.sloads_on_path.get(address, None)
            if reads is None:
                return  # unknown block: explore it
            # SMT-checked footprint intersection (symbolic locations compare
            # by satisfiability, reference dependency_pruner.py:142-195);
            # the currently-influencing loads count as reads too
            if not may_intersect(
                reads | annotation.storage_loaded, written, self._pair_cache
            ):
                log.debug("pruning block at %d (no storage dependency)", address)
                raise PluginSkipState

        def add_world_state_hook(global_state: GlobalState):
            annotation = get_dependency_annotation(global_state)
            ws_annotation = get_ws_dependency_annotation(global_state)
            # reset per-tx tracking; only storage_written carries over to the
            # next transaction (reference dependency_pruner.py:331-336) — an
            # uncleared storage_loaded would make every later footprint check
            # intersect and silently disable the pruner
            annotation.path = [0]
            annotation.storage_loaded = set()
            ws_annotation.annotations_stack.append(annotation)

        symbolic_vm.register_laser_hooks("start_sym_trans", start_sym_trans_hook)
        symbolic_vm.register_laser_hooks("add_world_state", add_world_state_hook)
        symbolic_vm.register_hooks(
            "pre",
            {
                "SLOAD": [sload_hook],
                "SSTORE": [sstore_hook],
                "CALL": [call_hook],
                "STATICCALL": [call_hook],
                "JUMPDEST": [jump_hook],
            },
        )


class DependencyPrunerBuilder(PluginBuilder):
    name = "dependency-pruner"

    def __call__(self, *args, **kwargs) -> LaserPlugin:
        return DependencyPruner()
