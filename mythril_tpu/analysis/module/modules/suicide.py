"""AccidentallyKillable: anyone can reach SELFDESTRUCT (SWC-106).

Reference parity: mythril/analysis/module/modules/suicide.py:54-126 — try
proving the attacker controls the beneficiary first, fall back to plain
reachability by the attacker.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from mythril_tpu.analysis.issue_annotation import IssueAnnotation
from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import UNPROTECTED_SELFDESTRUCT
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core.transaction.symbolic import ACTORS
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.smt import And

log = logging.getLogger(__name__)

DESCRIPTION = """
Check if the contact can be 'accidentally' killed by anyone.
For kill-able contracts, also check whether it is possible to direct the contract balance to the attacker.
"""


class AccidentallyKillable(DetectionModule):
    name = "Contract can be accidentally killed by anyone"
    swc_id = UNPROTECTED_SELFDESTRUCT
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["SELFDESTRUCT"]
    # staticpass: nothing to report without a SELFDESTRUCT
    static_required_ops = frozenset({"SELFDESTRUCT"})

    def __init__(self):
        super().__init__()
        self._cache_address = {}

    def _execute(self, state: GlobalState) -> Optional[List[Issue]]:
        if self._cache_key(state) in self.cache:
            return None
        return self._analyze_state(state)

    def _analyze_state(self, state: GlobalState) -> List[Issue]:
        instruction = state.get_current_instruction()
        to = state.mstate.stack[-1]

        log.debug("SELFDESTRUCT in function %s", state.node.function_name if state.node else "?")

        description_head = "Any sender can cause the contract to self-destruct."

        constraints = state.world_state.constraints.get_all_constraints()
        attacker_constraints = [
            tx.caller == ACTORS.attacker
            for tx in state.world_state.transaction_sequence
            if not _is_creation(tx)
        ]

        try:
            # strongest claim first: attacker receives the balance
            try:
                transaction_sequence = get_transaction_sequence(
                    state,
                    constraints
                    + attacker_constraints
                    + [to == ACTORS.attacker],
                )
                description_tail = (
                    "Any sender can trigger execution of the SELFDESTRUCT instruction to "
                    "destroy this contract and withdraw its balance to an arbitrary "
                    "address. Review the transaction sequence to see how this is possible."
                )
            except UnsatError:
                transaction_sequence = get_transaction_sequence(
                    state, constraints + attacker_constraints
                )
                description_tail = (
                    "Any sender can trigger execution of the SELFDESTRUCT instruction to "
                    "destroy this contract. Review the transaction sequence to see how "
                    "this is possible."
                )
        except UnsatError:
            return []

        issue = Issue(
            contract=state.environment.active_account.contract_name,
            function_name=state.node.function_name if state.node else "unknown",
            address=instruction["address"],
            swc_id=UNPROTECTED_SELFDESTRUCT,
            bytecode=state.environment.code.bytecode,
            title="Unprotected Selfdestruct",
            severity="High",
            description_head=description_head,
            description_tail=description_tail,
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )
        state.annotate(
            IssueAnnotation(conditions=[And(*constraints)], issue=issue, detector=self)
        )
        return [issue]


def _is_creation(tx) -> bool:
    from mythril_tpu.core.transaction.transaction_models import ContractCreationTransaction

    return isinstance(tx, ContractCreationTransaction)


detector = AccidentallyKillable
