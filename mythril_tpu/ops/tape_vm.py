"""Tape VM: ONE compiled XLA program evaluates ANY constraint conjunction.

The first device-probe design (mythril_tpu/ops/lowering.py) compiles each
distinct conjunction into its own jitted evaluator.  Engine workloads produce
a fresh conjunction per JUMPI fork, so that design pays an XLA compile —
seconds, and worse over a tunneled TPU — for almost every query; measured on
the killbilly benchmark the compile path was ~4s per dispatch, 1000x slower
than the host evaluator.

This module fixes the economics the TPU-native way: the *program* is a
generic term-tape interpreter compiled once per (profile, batch) bucket, and
the *conjunction* is data — opcode/operand/width-mask tensors streamed in
per query.  `lax.scan` walks the tape; `lax.switch` dispatches each step to
one of ~20 vector op kernels from mythril_tpu/ops/bitvec.py operating on the
whole candidate batch at once.  All values live as 256-bit (16xu32-limb)
words zero-extended from their semantic width; narrower-width semantics are
recovered by desugaring (signed compares via sign-bit flips, sext via
conditional OR of the extension mask, ashr/sdiv via 256-bit sign extension)
plus a per-step result mask, so every branch is width-static.

Array reads (select) resolve against per-candidate finite tables exactly as
in lowering.py; keccak terms hash concretely on device via
mythril_tpu/ops/keccak_jax.py (the 32- and 64-byte preimage shapes that EVM
storage-slot hashing produces).  Unsupported structure raises
`TapeUnsupported` and the caller falls back to the per-conjunction path.
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import mythril_tpu
from mythril_tpu.ops import bitvec as bv

mythril_tpu.enable_persistent_compilation_cache()

log = logging.getLogger(__name__)
from mythril_tpu.ops.keccak_jax import keccak256
from mythril_tpu.smt import terms
from mythril_tpu.smt.terms import Term

L = 16  # limbs per word (256 bits as 16x16-bit limbs in u32)

(
    OP_ADD, OP_SUB, OP_MUL, OP_UDIV, OP_UREM, OP_SDIV, OP_SREM, OP_EXP,
    OP_AND, OP_OR, OP_XOR, OP_SHL, OP_LSHR, OP_ASHR,
    OP_EQ, OP_ULT, OP_ITE, OP_SELECT, OP_KECCAK32, OP_KECCAK64,
) = range(20)

N_OPS = 20


class TapeUnsupported(Exception):
    """Conjunction shape the tape VM cannot express; use the fallback path."""


# Profiles: (T steps, V leaf slots, A arrays, K table rows, R roots)
_PROFILES = (
    ("small", 96, 24, 3, 8, 24),
    ("large", 384, 72, 6, 24, 72),
)
_BATCH_BUCKETS = (64, 256)


# ---------------------------------------------------------------------------
# Host-side tape assembly
# ---------------------------------------------------------------------------


class TapeProgram:
    """A conjunction assembled into tape tensors (numpy, device-ready)."""

    def __init__(self, conjuncts: Sequence[Term]):
        self.conjuncts = list(conjuncts)
        self.leaf_vars: List[Term] = []  # creation order == leaf-row order
        self.bv_vars: List[Term] = []
        self.bool_vars: List[Term] = []
        self.array_vars: List[Term] = []
        self._row_of: Dict[int, int] = {}  # term tid -> reg row
        self._const_rows: Dict[int, int] = {}  # value -> leaf row
        self._leaf_consts: List[int] = []  # leaf row -> const value
        self._var_rows: Dict[int, int] = {}  # var tid -> leaf row
        self.ops: List[Tuple[int, int, int, int, int, int]] = []  # op,a0,a1,a2,aux,wmask_width
        self.root_rows: List[int] = []
        self._build()

    # -- leaf management ----------------------------------------------------
    def _const(self, value: int) -> int:
        row = self._const_rows.get(value)
        if row is None:
            row = len(self._leaf_consts)
            self._leaf_consts.append(value)
            self._const_rows[value] = row
        return row

    @property
    def n_leaves(self) -> int:
        return len(self._leaf_consts) + len(self.leaf_vars)

    def _var_row(self, t: Term) -> int:
        row = self._var_rows.get(t.tid)
        if row is None:
            # var leaf rows sit above all const rows; the const count grows
            # while building, so store a placeholder (-1 - ordinal) that
            # finalize resolves once the const pool is complete
            row = -(1 + len(self.leaf_vars))
            self.leaf_vars.append(t)
            if t.sort is terms.BOOL:
                self.bool_vars.append(t)
            else:
                self.bv_vars.append(t)
            self._var_rows[t.tid] = row
        return row

    # -- op emission ---------------------------------------------------------
    def _emit(self, op: int, a0: int, a1: int = 0, a2: int = 0, aux: int = 0,
              width: int = 256) -> int:
        self.ops.append((op, a0, a1, a2, aux, width))
        if len(self.ops) > _PROFILES[-1][1]:
            raise TapeUnsupported("tape too long")
        # computed rows live above ALL leaf rows; encode as offset + big base
        return _STEP_BASE + len(self.ops) - 1

    def _build(self):
        for t in terms.topo_order(self.conjuncts):
            op = t.op
            if op in ("array_var", "const_array", "store"):
                if op == "array_var":
                    self.array_vars.append(t)
                    if len(self.array_vars) > _PROFILES[-1][3]:
                        raise TapeUnsupported("too many arrays")
                continue
            if op == "ite" and terms.is_array_sort(t.sort):
                continue
            if terms.is_bv_sort(t.sort) and t.width > 256:
                # wide terms (keccak preimage concats) are consumed
                # structurally by _lower_keccak; any other consumer will
                # fail the _r lookup and trigger the fallback path
                continue
            self._row_of[t.tid] = self._lower(t)
        for c in self.conjuncts:
            self.root_rows.append(self._row_of[c.tid])
        if len(self.root_rows) > _PROFILES[-1][5]:
            raise TapeUnsupported("too many roots")

    def _r(self, t: Term) -> int:
        row = self._row_of.get(t.tid)
        if row is None:
            raise TapeUnsupported(f"consumer of unlowered term {t.op}")
        return row

    def _lower(self, t: Term) -> int:
        op, a = t.op, t.args
        if op == "const":
            if t.sort is terms.BOOL:
                return self._const(1 if t.aux else 0)
            if t.width > 256:
                raise TapeUnsupported("wide constant")
            return self._const(t.aux)
        if op == "var":
            return self._var_row(t)
        if op == "select":
            return self._lower_select(a[0], self._r(a[1]))
        if op == "keccak":
            return self._lower_keccak(t)
        if op == "apply":
            raise TapeUnsupported("uninterpreted function")

        w = t.width if terms.is_bv_sort(t.sort) else 1

        if op == "and" or op == "or":
            code = OP_AND if op == "and" else OP_OR
            row = self._r(a[0])
            for x in a[1:]:
                row = self._emit(code, row, self._r(x), width=1)
            return row
        if op == "not":
            return self._emit(OP_XOR, self._r(a[0]), self._const(1), width=1)
        if op == "xor" and t.sort is terms.BOOL:
            return self._emit(OP_XOR, self._r(a[0]), self._r(a[1]), width=1)
        if op == "eq":
            if terms.is_array_sort(a[0].sort):
                raise TapeUnsupported("array equality")
            return self._emit(OP_EQ, self._r(a[0]), self._r(a[1]), width=1)
        if op == "ite":
            return self._emit(
                OP_ITE, self._r(a[0]), self._r(a[1]), self._r(a[2]), width=w
            )
        if op == "ult":
            return self._emit(OP_ULT, self._r(a[0]), self._r(a[1]), width=1)
        if op == "ule":
            lt = self._emit(OP_ULT, self._r(a[1]), self._r(a[0]), width=1)
            return self._emit(OP_XOR, lt, self._const(1), width=1)
        if op in ("slt", "sle"):
            wa = a[0].width
            sb = self._const(1 << (wa - 1))
            fa = self._emit(OP_XOR, self._r(a[0]), sb, width=wa)
            fb = self._emit(OP_XOR, self._r(a[1]), sb, width=wa)
            if op == "slt":
                return self._emit(OP_ULT, fa, fb, width=1)
            lt = self._emit(OP_ULT, fb, fa, width=1)
            return self._emit(OP_XOR, lt, self._const(1), width=1)

        if op == "bvnot":
            return self._emit(
                OP_XOR, self._r(a[0]), self._const(terms.mask(-1, w)), width=w
            )
        if op == "bvneg":
            return self._emit(OP_SUB, self._const(0), self._r(a[0]), width=w)
        if op == "zext":
            return self._r(a[0])  # invariant: regs are zero-extended already
        if op == "sext":
            return self._sign_extend(self._r(a[0]), a[0].width, w)
        if op == "extract":
            hi, lo = t.aux
            if lo == 0:
                # masking alone suffices; reuse the operand row via OR 0
                return self._emit(OP_OR, self._r(a[0]), self._const(0), width=w)
            return self._emit(
                OP_LSHR, self._r(a[0]), self._const(lo), width=w
            )
        if op == "concat":
            shifted = self._emit(
                OP_SHL, self._r(a[0]), self._const(a[1].width), width=w
            )
            return self._emit(OP_OR, shifted, self._r(a[1]), width=w)
        if op == "bvashr":
            ext = self._sign_extend(self._r(a[0]), w, 256)
            return self._emit(OP_ASHR, ext, self._r(a[1]), width=w)
        if op in ("bvsdiv", "bvsrem"):
            ea = self._sign_extend(self._r(a[0]), w, 256)
            eb = self._sign_extend(self._r(a[1]), w, 256)
            code = OP_SDIV if op == "bvsdiv" else OP_SREM
            return self._emit(code, ea, eb, width=w)
        simple = {
            "bvadd": OP_ADD, "bvsub": OP_SUB, "bvmul": OP_MUL,
            "bvudiv": OP_UDIV, "bvurem": OP_UREM, "bvexp": OP_EXP,
            "bvand": OP_AND, "bvor": OP_OR, "bvxor": OP_XOR,
            "bvshl": OP_SHL, "bvlshr": OP_LSHR,
        }
        code = simple.get(op)
        if code is None:
            raise TapeUnsupported(f"op {op}")
        return self._emit(code, self._r(a[0]), self._r(a[1]), width=w)

    def _sign_extend(self, row: int, from_w: int, to_w: int) -> int:
        if from_w >= to_w:
            return row
        sign = self._emit(OP_LSHR, row, self._const(from_w - 1), width=1)
        ext_bits = terms.mask(-1, to_w) ^ terms.mask(-1, from_w)
        extended = self._emit(
            OP_OR, row, self._const(ext_bits), width=to_w
        )
        return self._emit(OP_ITE, sign, extended, row, width=to_w)

    def _lower_select(self, arr: Term, idx_row: int) -> int:
        rng_w = arr.sort[2]
        if rng_w > 256 or arr.sort[1] > 256:
            raise TapeUnsupported("wide array sorts")
        if arr.op == "store":
            base, s_idx, s_val = arr.args
            below = self._lower_select(base, idx_row)
            hit = self._emit(OP_EQ, self._r(s_idx), idx_row, width=1)
            return self._emit(
                OP_ITE, hit, self._r(s_val), below, width=rng_w
            )
        if arr.op == "ite":
            c, x, y = arr.args
            then = self._lower_select(x, idx_row)
            els = self._lower_select(y, idx_row)
            return self._emit(
                OP_ITE, self._r(c), then, els, width=rng_w
            )
        if arr.op == "const_array":
            return self._r(arr.args[0])
        if arr.op == "array_var":
            slot = next(
                i for i, av in enumerate(self.array_vars) if av.tid == arr.tid
            )
            return self._emit(OP_SELECT, idx_row, aux=slot, width=rng_w)
        raise TapeUnsupported(f"array op {arr.op}")

    def _lower_keccak(self, t: Term) -> int:
        inp = t.args[0]
        if inp.width == 256:
            return self._emit(OP_KECCAK32, self._r(inp), width=256)
        if inp.width == 512 and inp.op == "concat":
            hi, lo = inp.args
            if hi.width == 256 and lo.width == 256:
                return self._emit(
                    OP_KECCAK64, self._r(lo), self._r(hi), width=256
                )
        raise TapeUnsupported(f"keccak input width {inp.width}")

    # -- finalize into padded tensors ---------------------------------------
    def finalize(self, profile) -> Optional[dict]:
        """Resolve rows against a profile; None if the profile is too small."""
        name, T, V, A, K, R = profile
        n_consts = len(self._leaf_consts)
        if (
            len(self.ops) > T
            or self.n_leaves > V
            or len(self.array_vars) > A
            or len(self.root_rows) > R
        ):
            return None

        def resolve(row: int) -> int:
            if row >= _STEP_BASE:
                return V + (row - _STEP_BASE)
            if row < 0:
                return n_consts + (-row - 1)  # var placeholder
            return row  # const leaf

        op = np.zeros(T, np.int32)
        a0 = np.zeros(T, np.int32)
        a1 = np.zeros(T, np.int32)
        a2 = np.zeros(T, np.int32)
        aux = np.zeros(T, np.int32)
        wmask = np.zeros((T, L), np.uint32)
        for i, (o, x0, x1, x2, ax, w) in enumerate(self.ops):
            op[i] = o
            a0[i] = resolve(x0)
            a1[i] = resolve(x1)
            a2[i] = resolve(x2)
            aux[i] = ax
            wmask[i] = bv.from_ints(terms.mask(-1, w), 256)
        root_rows = np.zeros(R, np.int32)
        root_valid = np.zeros(R, bool)
        for i, row in enumerate(self.root_rows):
            root_rows[i] = resolve(row)
            root_valid[i] = True
        leaf_consts = np.zeros((V, L), np.uint32)
        for i, v in enumerate(self._leaf_consts):
            leaf_consts[i] = bv.from_ints(v, 256)
        return {
            "profile": name,
            "shape": (T, V, A, K, R),
            "op": op, "a0": a0, "a1": a1, "a2": a2, "aux": aux,
            "wmask": wmask, "root_rows": root_rows, "root_valid": root_valid,
            "leaf_consts": leaf_consts, "n_consts": n_consts,
        }


_STEP_BASE = 1 << 20


# ---------------------------------------------------------------------------
# The compiled interpreter (one jit per (profile shape, batch bucket))
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("T", "V", "A", "K", "R"))
def _run_tape(
    leaf_vals,  # [B, V, L] u32 (consts + var values)
    tab_idx,  # [B, A, K, L] u32
    tab_val,  # [B, A, K, L] u32
    tab_valid,  # [B, A, K] bool
    tab_default,  # [B, A, L] u32
    op, a0, a1, a2, aux,  # [T] i32
    wmask,  # [T, L] u32
    root_rows,  # [R] i32
    root_valid,  # [R] bool
    *, T: int, V: int, A: int, K: int, R: int,
):
    B = leaf_vals.shape[0]
    regs0 = jnp.zeros((V + T, B, L), jnp.uint32)
    regs0 = regs0.at[:V].set(jnp.transpose(leaf_vals, (1, 0, 2)))

    def to_word(flag):  # [B] bool -> [B, L] 0/1 word
        out = jnp.zeros((B, L), jnp.uint32)
        return out.at[:, 0].set(flag.astype(jnp.uint32))

    def br_select(x, y, z, slot):
        t_idx = lax.dynamic_index_in_dim(tab_idx, slot, axis=1, keepdims=False)
        t_val = lax.dynamic_index_in_dim(tab_val, slot, axis=1, keepdims=False)
        t_ok = lax.dynamic_index_in_dim(tab_valid, slot, axis=1, keepdims=False)
        t_def = lax.dynamic_index_in_dim(tab_default, slot, axis=1, keepdims=False)
        hit = (t_idx == x[:, None, :]).all(-1) & t_ok  # [B, K]
        any_hit = hit.any(-1)
        chosen = (t_val * hit[..., None].astype(jnp.uint32)).sum(axis=1)
        return jnp.where(any_hit[:, None], chosen, t_def)

    def br_keccak64(x, y, z, slot):
        # x = low 256 bits, y = high 256 bits; limbs little-endian
        return keccak256(jnp.concatenate([x, y], axis=-1), 512)

    branches = [
        lambda x, y, z, s: bv.add(x, y, 256),
        lambda x, y, z, s: bv.sub(x, y, 256),
        lambda x, y, z, s: bv.mul(x, y, 256),
        lambda x, y, z, s: bv.udiv(x, y, 256),
        lambda x, y, z, s: bv.urem(x, y, 256),
        lambda x, y, z, s: bv.sdiv(x, y, 256),
        lambda x, y, z, s: bv.srem(x, y, 256),
        lambda x, y, z, s: bv.bvexp(x, y, 256),
        lambda x, y, z, s: x & y,
        lambda x, y, z, s: x | y,
        lambda x, y, z, s: x ^ y,
        lambda x, y, z, s: bv.shl(x, y, 256),
        lambda x, y, z, s: bv.lshr(x, y, 256),
        lambda x, y, z, s: bv.ashr(x, y, 256),
        lambda x, y, z, s: to_word(bv.eq(x, y)),
        lambda x, y, z, s: to_word(bv.ult(x, y)),
        lambda x, y, z, s: bv.mux((x != 0).any(-1), y, z),
        br_select,
        lambda x, y, z, s: keccak256(x, 256),
        br_keccak64,
    ]

    def step_wrapper(carry, xs):
        regs, t = carry
        opc, i0, i1, i2, slot, wm = xs
        x = lax.dynamic_index_in_dim(regs, i0, axis=0, keepdims=False)
        y = lax.dynamic_index_in_dim(regs, i1, axis=0, keepdims=False)
        z = lax.dynamic_index_in_dim(regs, i2, axis=0, keepdims=False)
        res = lax.switch(opc, branches, x, y, z, slot)
        res = res & wm[None, :]
        regs = lax.dynamic_update_index_in_dim(regs, res, V + t, axis=0)
        return (regs, t + 1), None

    (regs, _), _ = lax.scan(
        step_wrapper, (regs0, jnp.int32(0)), (op, a0, a1, a2, aux, wmask)
    )
    vals = regs[root_rows]  # [R, B, L] (static gather: root_rows is traced...)
    truth = (vals != 0).any(-1)  # [R, B]
    truth = truth | ~root_valid[:, None]
    return truth.T  # [B, R]


# ---------------------------------------------------------------------------
# Public adapter (mirrors lowering.CompiledConjunction's surface)
# ---------------------------------------------------------------------------


class TapeCompiled:
    """Evaluate a conjunction over candidate batches via the shared VM."""

    def __init__(self, program: TapeProgram, tensors: dict):
        self.program = program
        self.tensors = tensors
        self.conjuncts = program.conjuncts
        self.bv_vars = program.bv_vars
        self.bool_vars = program.bool_vars
        self.array_vars = program.array_vars

    def evaluate_batch(self, assignments) -> np.ndarray:
        args, (T, V, A, K, R) = self.pack_args(assignments)
        truth = _run_tape(*args, T=T, V=V, A=A, K=K, R=R)
        return np.asarray(truth)[: len(assignments), : len(self.conjuncts)]

    def pack_args(self, assignments) -> Tuple[tuple, tuple]:
        """Candidate assignments -> the _run_tape input tensors + shape.

        Exposed separately so callers embedding the interpreter in larger
        jitted programs (driver entry points, mesh-sharded dispatch) can
        build the exact argument tuple the compiled program expects.
        """
        t = self.tensors
        T, V, A, K, R = t["shape"]
        B_real = len(assignments)
        B = next((b for b in _BATCH_BUCKETS if b >= B_real), None)
        if B is None:
            B = ((B_real + 255) // 256) * 256

        # packing is bulk per column (one from_ints call over the whole
        # batch) — per-candidate Python loops were the large-batch bottleneck
        leaf_vals = np.tile(t["leaf_consts"][None], (B, 1, 1))
        n_consts = t["n_consts"]
        n = len(assignments)
        for vi, var in enumerate(self.program.leaf_vars):
            vals = [int(asg.scalars.get(var, 0)) for asg in assignments]
            leaf_vals[:n, n_consts + vi] = bv.from_ints(vals, 256)

        tab_idx = np.zeros((B, A, K, L), np.uint32)
        tab_val = np.zeros((B, A, K, L), np.uint32)
        tab_valid = np.zeros((B, A, K), bool)
        tab_default = np.zeros((B, A, L), np.uint32)
        for ai, av in enumerate(self.program.array_vars):
            keys = sorted(
                {
                    k
                    for asg in assignments
                    for k in getattr(asg.arrays.get(av), "backing", {})
                }
            )[:K]
            arrs = [asg.arrays.get(av) for asg in assignments]
            defaults = [int(a.default) if a is not None else 0 for a in arrs]
            tab_default[:n, ai] = bv.from_ints(defaults, 256)
            if keys:
                tab_idx[:, ai, : len(keys)] = bv.from_ints(keys, 256)[None]
                tab_valid[:n, ai, : len(keys)] = True
                for ki, k in enumerate(keys):
                    vals = [
                        int(a.backing.get(k, d)) if a is not None else 0
                        for a, d in zip(arrs, defaults)
                    ]
                    tab_val[:n, ai, ki] = bv.from_ints(vals, 256)

        args = (
            jnp.asarray(leaf_vals),
            jnp.asarray(tab_idx),
            jnp.asarray(tab_val),
            jnp.asarray(tab_valid),
            jnp.asarray(tab_default),
            jnp.asarray(t["op"]), jnp.asarray(t["a0"]), jnp.asarray(t["a1"]),
            jnp.asarray(t["a2"]), jnp.asarray(t["aux"]),
            jnp.asarray(t["wmask"]),
            jnp.asarray(t["root_rows"]), jnp.asarray(t["root_valid"]),
        )
        return args, (T, V, A, K, R)


import threading

_warm_lock = threading.Lock()
_warm_state = "cold"  # cold | warming | ready
_warm_event = threading.Event()


def _do_warmup_compiles() -> None:
    from mythril_tpu.smt import terms
    from mythril_tpu.smt.concrete_eval import Assignment

    x = terms.var("__tape_warmup__", 256)
    compiled = compile_tape([terms.ult(x, terms.const(7, 256))])
    asg = Assignment()
    asg.scalars[x] = 1
    # both production batch buckets: is_possible dispatches 48 candidates
    # (-> bucket 64), get_model dispatches 192 (-> bucket 256)
    for b in _BATCH_BUCKETS:
        compiled.evaluate_batch([asg] * b)


def _run_claimed_warmup() -> None:
    """Body for a caller that already moved the state to 'warming'."""
    global _warm_state
    try:
        _do_warmup_compiles()
        with _warm_lock:
            _warm_state = "ready"
    except BaseException:
        with _warm_lock:
            _warm_state = "cold"  # allow a later retry
        raise
    finally:
        _warm_event.set()


def warmup() -> None:
    """Pre-compile the interpreter for the common (profile, batch) buckets.

    Engine timers (notably the 10s creation-transaction timeout, reference
    cli default) must not pay the one-time interpreter compile; callers that
    are about to start timed symbolic execution with a FORCED device backend
    invoke this synchronously (waiting for an in-flight background warm-up
    rather than duplicating it).  The "auto" backend instead calls
    ``ensure_warming`` (non-blocking) and keeps using the host path until
    ``interpreter_ready`` — the compile can take tens of seconds over a
    tunneled TPU, which small workloads would never amortize.
    """
    global _warm_state
    while True:
        with _warm_lock:
            if _warm_state == "ready":
                return
            if _warm_state == "cold":
                _warm_state = "warming"
                _warm_event.clear()
                claimed = True
            else:
                claimed = False
        if claimed:
            _run_claimed_warmup()
            return
        _warm_event.wait()  # another thread is compiling; re-check after


def ensure_warming() -> None:
    """Kick the interpreter compile on a background thread (idempotent).

    The claim happens HERE under the lock (before the thread starts), so
    back-to-back callers can never spawn duplicate compile threads.
    Deliberately NOT a daemon thread: interpreter shutdown while an XLA
    compile is in flight aborts the process ("FATAL: exception not
    rethrown"), so exit waits for the compile to finish.  Callers only kick
    this once a query has actually crossed the device break-even, so short
    host-only runs never start (or wait for) it.
    """
    global _warm_state
    with _warm_lock:
        if _warm_state != "cold":
            return
        _warm_state = "warming"
        _warm_event.clear()

    def _guarded():
        try:
            _run_claimed_warmup()
        except Exception:
            log.warning("background tape-VM warmup failed; will retry", exc_info=True)

    threading.Thread(target=_guarded, daemon=False, name="tape-vm-warmup").start()


def interpreter_ready() -> bool:
    return _warm_state == "ready"


_CACHE: Dict[tuple, TapeCompiled] = {}
_CACHE_CAP = 4096


def compile_tape(conjuncts: Sequence[Term]) -> TapeCompiled:
    """Assemble (and cache) the tape for a conjunction.

    Raises TapeUnsupported when the DAG exceeds every profile or contains
    structure the VM cannot express; callers fall back to
    lowering.compile_cached.
    """
    key = tuple(c.tid for c in conjuncts)
    hit = _CACHE.get(key)
    if hit is not None:
        return hit
    program = TapeProgram(conjuncts)
    tensors = None
    for profile in _PROFILES:
        tensors = program.finalize(profile)
        if tensors is not None:
            break
    if tensors is None:
        raise TapeUnsupported("exceeds every profile")
    compiled = TapeCompiled(program, tensors)
    if len(_CACHE) >= _CACHE_CAP:
        _CACHE.clear()
    _CACHE[key] = compiled
    return compiled
