"""Dependency pruner: skip blocks that can't touch storage written earlier.

Reference parity: mythril/laser/plugin/plugins/dependency_pruner.py:142-318 —
builds a cross-transaction map of storage locations read per basic block; in
transaction N >= 2, a path is skipped when the blocks it is about to execute
cannot read any location written by the previous transactions.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Set

from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.plugins.interface import LaserPlugin, PluginBuilder
from mythril_tpu.plugins.plugin_annotations import (
    DependencyAnnotation,
    WSDependencyAnnotation,
)
from mythril_tpu.plugins.signals import PluginSkipState

log = logging.getLogger(__name__)


def get_dependency_annotation(state: GlobalState) -> DependencyAnnotation:
    annotations = state.get_annotations(DependencyAnnotation)
    if annotations:
        return annotations[0]
    # inherit from the world state's annotation stack if present
    ws_annotations = state.world_state.get_annotations(WSDependencyAnnotation)
    if ws_annotations and ws_annotations[0].annotations_stack:
        annotation = ws_annotations[0].annotations_stack[-1].__copy__()
    else:
        annotation = DependencyAnnotation()
    state.annotate(annotation)
    return annotation


def get_ws_dependency_annotation(state: GlobalState) -> WSDependencyAnnotation:
    ws_annotations = state.world_state.get_annotations(WSDependencyAnnotation)
    if ws_annotations:
        return ws_annotations[0]
    annotation = WSDependencyAnnotation()
    state.world_state.annotate(annotation)
    return annotation


class DependencyPruner(LaserPlugin):
    def __init__(self):
        self.sloads_on_path: Dict[int, Set] = {}
        self.iteration = 0

    def initialize(self, symbolic_vm) -> None:
        self.iteration = 0

        def start_sym_trans_hook():
            self.iteration += 1

        def sload_hook(global_state: GlobalState):
            annotation = get_dependency_annotation(global_state)
            index = global_state.mstate.stack[-1]
            key = index.value if index.value is not None else repr(index.raw)
            annotation.storage_loaded.add(key)
            for block in annotation.path:
                self.sloads_on_path.setdefault(block, set()).add(key)

        def sstore_hook(global_state: GlobalState):
            annotation = get_dependency_annotation(global_state)
            index = global_state.mstate.stack[-1]
            key = index.value if index.value is not None else repr(index.raw)
            annotation.extend_storage_write_cache(self.iteration, key)

        def call_hook(global_state: GlobalState):
            annotation = get_dependency_annotation(global_state)
            annotation.has_call = True

        def jump_hook(global_state: GlobalState):
            annotation = get_dependency_annotation(global_state)
            address = global_state.get_current_instruction()["address"]
            annotation.path.append(address)
            if self.iteration < 2:
                return
            if annotation.has_call:
                return
            # would this block possibly read something written before?
            written = set()
            for it in range(self.iteration):
                written |= annotation.storage_written.get(it, set())
            ws_annotation = get_ws_dependency_annotation(global_state)
            for dep in ws_annotation.annotations_stack:
                for it, keys in dep.storage_written.items():
                    written |= keys
            reads = self.sloads_on_path.get(address, None)
            if reads is None:
                return  # unknown block: explore it
            symbolic_read = any(isinstance(k, str) for k in reads)
            symbolic_write = any(isinstance(k, str) for k in written)
            if symbolic_read or symbolic_write:
                return
            if not (reads & written):
                log.debug("pruning block at %d (no storage dependency)", address)
                raise PluginSkipState

        def add_world_state_hook(global_state: GlobalState):
            annotation = get_dependency_annotation(global_state)
            ws_annotation = get_ws_dependency_annotation(global_state)
            ws_annotation.annotations_stack.append(annotation)

        symbolic_vm.register_laser_hooks("start_sym_trans", start_sym_trans_hook)
        symbolic_vm.register_laser_hooks("add_world_state", add_world_state_hook)
        symbolic_vm.register_hooks(
            "pre",
            {
                "SLOAD": [sload_hook],
                "SSTORE": [sstore_hook],
                "CALL": [call_hook],
                "STATICCALL": [call_hook],
                "JUMPDEST": [jump_hook],
            },
        )


class DependencyPrunerBuilder(PluginBuilder):
    name = "dependency-pruner"

    def __call__(self, *args, **kwargs) -> LaserPlugin:
        return DependencyPruner()
