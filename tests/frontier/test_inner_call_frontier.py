"""Inner message-call frames as frontier seeds (SURVEY.md §7.4 item 4).

A CALL parks the CALLER to the host (call setup is host-orchestrated), but
the CALLEE's fresh frame is an eligible seed: with periodic re-drains inside
the host loop, the callee body executes device-resident as its own
multi-code batch member, its terminal replays through the host transaction
end, and the resumed caller continues on the host work list — the
"host-orchestrated nested segment" design (reference svm.py:386-445).
"""

import pytest

from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.frontier.stats import FrontierStatistics
from mythril_tpu.support.support_args import args as global_args


def _self_call_contract() -> bytes:
    """fn outer(): writes calldataload(4) to memory, CALLs self with it as
    the inner calldata (selector inner()), SSTOREs the call's success flag;
    fn inner(): forks on its argument word and SELFDESTRUCTs on one branch
    — symbolic width INSIDE the callee frame."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).parents[2]))
    from bench_contracts import Asm

    a = Asm()
    # dispatcher on first calldata byte (kept primitive on purpose)
    a.push(0).op("CALLDATALOAD").push(0xF8).op("SHR")
    a.op("DUP1").push(0x01).op("EQ").jumpi("outer")
    a.op("DUP1").push(0x02).op("EQ").jumpi("inner")
    a.revert()

    a.label("outer")
    # memory[0] = selector byte for inner (0x02 << 248); memory[1..33) = arg
    a.push(0x02).push(248).op("SHL").push(0).op("MSTORE")
    a.push(4).op("CALLDATALOAD").push(1).op("MSTORE")
    # call(gas, address(this), 0, 0, 33, 64, 32)
    a.push(32).push(64).push(33).push(0).push(0)
    a.op("ADDRESS")
    a.push(50000)
    a.op("CALL")
    a.push(0).op("SSTORE")
    a.op("STOP")

    a.label("inner")
    # fork on the argument word: JUMPI chain over two bits, then the
    # vulnerable branch selfdestructs (detectable through the inner frame)
    a.push(1).op("CALLDATALOAD")
    a.op("DUP1").push(1).op("AND").jumpi("inner_kill")
    a.op("POP")
    a.push(1).push(0).op("MSTORE").push(32).push(0).op("RETURN")
    a.label("inner_kill")
    a.op("POP", "CALLER")
    a.op("SELFDESTRUCT")

    # ADDRESS opcode is not in the minimal Asm table: patch it in
    return a.assemble()



def _analyze(code: bytes, frontier: bool):
    reset_callback_modules()
    from mythril_tpu.analysis.module.loader import ModuleLoader

    for m in ModuleLoader().get_detection_modules():
        if hasattr(m, "cache"):
            m.cache.clear()
    old = (global_args.frontier, global_args.frontier_force)
    global_args.frontier = frontier
    global_args.frontier_force = frontier
    try:
        sym = SymExecWrapper(
            code,
            address=0x0901D12E,
            strategy="bfs",
            transaction_count=1,
            execution_timeout=60,
            modules=["AccidentallyKillable"],
        )
        return fire_lasers(sym, white_list=["AccidentallyKillable"])
    finally:
        global_args.frontier, global_args.frontier_force = old


def keys(issues):
    return sorted((i.swc_id, i.address, i.function) for i in issues)


def test_inner_call_frame_runs_on_device_with_host_parity():
    code = _self_call_contract()
    host = _analyze(code, frontier=False)
    FrontierStatistics().reset()
    dev = _analyze(code, frontier=True)
    stats = FrontierStatistics().as_dict()
    assert keys(host) == keys(dev), (
        f"inner-call issues diverged: host={keys(host)} dev={keys(dev)}"
    )
    # the selfdestruct lives INSIDE the callee frame: finding it via the
    # frontier requires the inner frame to have executed (device or host
    # spill) and its terminal to resume the caller correctly
    assert any(i.swc_id == "106" for i in dev), "inner selfdestruct lost"
    assert stats["device_instructions"] > 0, "frontier never engaged"
    # mid-frame re-entry: the RESUMED caller (pc past the CALL, stack and
    # memory populated) must itself execute device instructions — round 3
    # left every resumed/parked state host-side forever
    assert stats["mid_injections"] > 0, (
        f"no mid-frame state re-entered the device: {stats}"
    )
    # residency telemetry: opcode parks on this workload are pinned
    # host-side until the host steps past the pc, and must be counted so
    # the mid-frame residency story is checkable per run
    assert stats["semantic_parks"] > 0, (
        f"opcode parks not counted as semantic parks: {stats}"
    )
