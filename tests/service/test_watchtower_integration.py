"""Watchtower wired into the daemon: breach drill over the real service.

One inline daemon (frontier off, warmup off) with a tight TTFE budget
and the admission fault hook armed — the injected stall must flow
through the service TTFE clock into a breach, the health surfaces
(``health()``, ``stats()``, the ``health`` protocol verb, Prometheus,
``format_health``) must all report it, and a clean daemon with honest
targets must stay green."""

import json
import time
from pathlib import Path

import pytest

from mythril_tpu.service import (
    AnalysisOptions,
    AnalysisService,
    ServiceConfig,
)

REPO = Path(__file__).resolve().parents[2]
KILL_SIMPLE_HEX = (
    REPO / "tests" / "testdata" / "inputs" / "kill_simple.bin-runtime"
).read_text().strip()

OPTS = AnalysisOptions(transaction_count=1, execution_timeout=30)


def _slo_file(tmp_path, target_s: float) -> str:
    path = tmp_path / "slo.json"
    path.write_text(json.dumps({
        "capture": {"profile": False},
        "objectives": [
            {"name": "ttfe_p95", "kind": "quantile",
             "metric": "service.ttfe_s", "q": 0.95, "target": target_s,
             "fast_window_s": 10, "slow_window_s": 30, "min_count": 1},
        ],
    }))
    return str(path)


def _config(tmp_path, slo, **overrides):
    base = dict(
        default_options=OPTS,
        max_batch_width=2,
        batch_window_s=0.1,
        frontier=False,
        probe=False,
        warmup=False,
        cache_root=str(tmp_path / "cache"),
        watchtower=True,
        watchtower_interval_s=0.2,
        slo_file=slo,
    )
    base.update(overrides)
    return ServiceConfig(**base)


@pytest.fixture(autouse=True)
def _clean_slo_metrics():
    # Reset service.* too: a young daemon's fast window starts before its
    # first history sample, so the window delta falls back to the lifetime
    # histogram — which in a full-suite run carries every prior service
    # test's TTFE observations and would drown the injected stall.
    from mythril_tpu.observability.metrics import get_registry

    get_registry().reset(include_persistent=True, prefix="slo.")
    get_registry().reset(include_persistent=True, prefix="service.")
    yield
    get_registry().reset(include_persistent=True, prefix="slo.")


def test_injected_stall_breaches_ttfe(scoped_args, tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_INJECT_ADMISSION_SLEEP", "0.6")
    service = AnalysisService(
        _config(tmp_path, _slo_file(tmp_path, target_s=0.05))
    ).start()
    try:
        _req, stream, _ = service.submit(
            KILL_SIMPLE_HEX, name="kill", tier="interactive"
        )
        assert list(stream.events(timeout=120))[-1][0] == "done"

        health = {}
        deadline = time.time() + 30
        while time.time() < deadline:
            health = service.health()
            if not health.get("ok"):
                break
            time.sleep(0.1)
        assert health["enabled"] is True
        assert health["ok"] is False
        assert "ttfe_p95" in health["breaching"]
        assert health["breaches_total"] >= 1
        (ev,) = [e for e in health["objectives"]
                 if e["name"] == "ttfe_p95"]
        # the stall happened BEFORE dispatch: it must land in TTFE
        assert ev["value"] >= 0.6
        assert ev["state"] == "breach"

        # every surface reports the same verdict
        assert service.stats()["health"]["ok"] is False
        from mythril_tpu.observability.metrics import prometheus_text

        text = prometheus_text()
        assert 'slo_status{objective="ttfe_p95"} 2' in text
        assert any(
            line.startswith("slo_breaches_total")
            and float(line.rsplit(" ", 1)[1]) >= 1
            for line in text.splitlines()
        )
        from mythril_tpu.service.top import format_health, format_top

        rendered = format_health(health, address="test:0")
        assert "BREACH" in rendered and "ttfe_p95" in rendered
        assert "!! SLO BREACH: ttfe_p95" in format_top(
            service.stats(), address="test:0")
    finally:
        service.stop(drain=True, timeout=60)

    # the watchtower was torn down with the daemon...
    from mythril_tpu.observability.watchtower import get_watchtower

    assert get_watchtower() is None
    # ...but the history ring survives under --cache-root
    from mythril_tpu.observability.history import HistoryReader

    reader = HistoryReader(str(tmp_path / "cache" / "history"))
    assert reader.segments()
    assert reader.series("service.requests")


def test_clean_daemon_stays_green(scoped_args, tmp_path):
    service = AnalysisService(
        _config(tmp_path, _slo_file(tmp_path, target_s=60.0))
    ).start()
    try:
        _req, stream, _ = service.submit(
            KILL_SIMPLE_HEX, name="kill", tier="interactive"
        )
        assert list(stream.events(timeout=120))[-1][0] == "done"
        time.sleep(0.5)  # at least two evaluation ticks
        health = service.health()
        assert health["enabled"] is True
        assert health["ok"] is True
        assert health["breaches_total"] == 0
        from mythril_tpu.service.top import format_top

        top = format_top(service.stats(), address="test:0")
        assert "slo: ok (1 objective" in top
        assert "BREACH" not in top
        # jsonv2 meta.health rides the same evaluation
        from mythril_tpu.observability.watchtower import health_meta

        meta = health_meta()
        assert meta["enabled"] and meta["ok"]
    finally:
        service.stop(drain=True, timeout=60)


def test_watchtower_disabled_health_shape(scoped_args, tmp_path):
    service = AnalysisService(ServiceConfig(
        default_options=OPTS, frontier=False, probe=False, warmup=False,
    )).start()
    try:
        health = service.health()
        assert health == {"enabled": False, "ok": None, "objectives": []}
        assert "health" not in service.stats()
        from mythril_tpu.service.top import format_health

        assert "disabled" in format_health(health)
    finally:
        service.stop(drain=True, timeout=60)
