"""Disassembler facade: load contracts from bytecode / address / solidity.

Reference parity: mythril/mythril/mythril_disassembler.py:26-318 — including
the on-chain storage-slot reader with mapping-slot keccak math and the solc
>= 0.8 integer-module toggle.
"""

from __future__ import annotations

import logging
import re
from typing import List, Optional, Tuple

from mythril_tpu.exceptions import CriticalError
from mythril_tpu.frontend.evmcontract import EVMContract
from mythril_tpu.frontend.soliditycontract import SolidityContract, get_contracts_from_file
from mythril_tpu.ops.keccak import keccak256
from mythril_tpu.support.loader import DynLoader
from mythril_tpu.support.signatures import SignatureDB
from mythril_tpu.support.support_args import args

log = logging.getLogger(__name__)


class MythrilDisassembler:
    def __init__(
        self,
        eth=None,
        solc_version: Optional[str] = None,
        solc_settings_json: Optional[str] = None,
        enable_online_lookup: bool = False,
    ):
        self.eth = eth
        self.solc_binary = self._init_solc_binary(solc_version)
        self.solc_settings_json = solc_settings_json
        self.enable_online_lookup = enable_online_lookup
        self.sigs = SignatureDB(enable_online_lookup=enable_online_lookup)
        self.contracts: List[EVMContract] = []

    @staticmethod
    def _init_solc_binary(version: Optional[str]) -> str:
        """Pick the solc binary; versioned binaries are expected on PATH as
        solc-vX.Y.Z (py-solc-x style management is unavailable offline)."""
        if not version:
            return "solc"
        if version.startswith("v"):
            version = version[1:]
        candidate = f"solc-v{version}"
        import shutil

        if shutil.which(candidate):
            return candidate
        log.warning("versioned solc %s not found; falling back to `solc`", candidate)
        return "solc"

    def load_from_bytecode(
        self, code: str, bin_runtime: bool = False, address: Optional[str] = None
    ) -> Tuple[str, EVMContract]:
        if address is None:
            address = "0x" + "0" * 38 + "06"
        code = code.replace("0x", "")
        if bin_runtime:
            contract = EVMContract(
                code=code, name="MAIN", enable_online_lookup=self.enable_online_lookup
            )
        else:
            contract = EVMContract(
                creation_code=code, name="MAIN", enable_online_lookup=self.enable_online_lookup
            )
        self.contracts.append(contract)
        self._refresh_integer_module()
        return address, contract

    def load_from_address(self, address: str) -> Tuple[str, EVMContract]:
        if not re.match(r"0x[a-fA-F0-9]{40}", address):
            raise CriticalError("invalid contract address")
        if self.eth is None:
            raise CriticalError(
                "please set an RPC provider (--rpc) to load contracts from the chain"
            )
        code = self.eth.eth_getCode(address)
        if not code or code == "0x":
            raise CriticalError("no code at the given address")
        contract = EVMContract(
            code=code[2:], name=address, enable_online_lookup=self.enable_online_lookup
        )
        self.contracts.append(contract)
        self._refresh_integer_module()
        return address, contract

    def load_from_solidity(
        self, solidity_files: List[str]
    ) -> Tuple[str, List[SolidityContract]]:
        address = "0x" + "0" * 38 + "06"
        contracts = []
        for file in solidity_files:
            if ":" in file:
                file_path, contract_name = file.rsplit(":", 1)
            else:
                file_path, contract_name = file, None
            if contract_name:
                contract = SolidityContract(
                    file_path,
                    name=contract_name,
                    solc_settings_json=self.solc_settings_json,
                    solc_binary=self.solc_binary,
                )
                contracts.append(contract)
            else:
                contracts.extend(
                    get_contracts_from_file(
                        file_path,
                        solc_settings_json=self.solc_settings_json,
                        solc_binary=self.solc_binary,
                    )
                )
        self.contracts.extend(contracts)
        self._refresh_integer_module()
        return address, contracts

    def _refresh_integer_module(self) -> None:
        """Re-derive args.use_integer_module over ALL queued contracts.

        solc >= 0.8 has checked arithmetic: disable the integer module only
        when EVERY contract queued on this disassembler (the analyzer runs
        them all) provably targets >= 0.8.  A contract without a readable
        pragma — including raw bytecode and on-chain loads — counts as
        unknown, keeping the module enabled.
        """
        pragmas = []
        for contract in self.contracts:
            files = getattr(contract, "solidity_files", None)
            source = files[0].code if files else ""
            pragma = re.search(r"pragma solidity\s+[^0-9]*0\.([0-9]+)", source)
            pragmas.append(int(pragma.group(1)) if pragma else 0)
        args.use_integer_module = not (pragmas and all(p >= 8 for p in pragmas))

    def get_state_variable_from_storage(self, address: str, params: List[str]) -> str:
        """Read storage slots, incl. mapping/array math (reference :200-318)."""
        (position, length, mappings) = (0, 1, [])
        out = ""
        try:
            if params[0] == "mapping":
                if len(params) < 3:
                    raise CriticalError("mapping requires: mapping <position> <key1> [...]")
                position = int(params[1])
                for key in params[2:]:
                    mappings.append(int(key, 0))
                position_formatted = position.to_bytes(32, "big")
                for mapping_idx in mappings:
                    key_formatted = mapping_idx.to_bytes(32, "big")
                    slot = int.from_bytes(
                        keccak256(key_formatted + position_formatted), "big"
                    )
                    value = self.eth.eth_getStorageAt(address, slot)
                    out += f"{hex(slot)}: {value}\n"
                return out
            position = int(params[0])
            if len(params) >= 2:
                length = int(params[1])
            if len(params) == 3 and params[2] == "array":
                position_formatted = position.to_bytes(32, "big")
                base = int.from_bytes(keccak256(position_formatted), "big")
                for i in range(length):
                    value = self.eth.eth_getStorageAt(address, base + i)
                    out += f"{hex(base + i)}: {value}\n"
                return out
            for i in range(position, position + length):
                value = self.eth.eth_getStorageAt(address, i)
                out += f"{i}: {value}\n"
            return out
        except ValueError as e:
            raise CriticalError(f"invalid storage index: {e}") from e
