"""Bytecode disassembler + function-selector recovery.

Reference parity: mythril/disassembler/asm.py (instruction listing, PUSH
argument capture, metadata trim) and mythril/disassembler/disassembly.py:40-115
(dispatcher-pattern scan recovering selector -> entrypoint maps).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from mythril_tpu.support.opcodes import BYTE_TO_NAME, OPCODES


class EvmInstruction:
    __slots__ = ("address", "opcode", "argument")

    def __init__(self, address: int, opcode: str, argument: Optional[bytes] = None):
        self.address = address
        self.opcode = opcode
        self.argument = argument  # PUSH payload, big-endian bytes

    @property
    def arg_int(self) -> Optional[int]:
        return int.from_bytes(self.argument, "big") if self.argument is not None else None

    def to_dict(self) -> Dict:
        d = {"address": self.address, "opcode": self.opcode}
        if self.argument is not None:
            d["argument"] = "0x" + self.argument.hex()
        return d

    def __repr__(self):
        if self.argument is not None:
            return f"{self.address} {self.opcode} 0x{self.argument.hex()}"
        return f"{self.address} {self.opcode}"


_METADATA_RE = re.compile(
    rb"\xa1\x65bzzr[01]|\xa2\x64ipfs|\xa2\x65bzzr[01]|\xa3\x64ipfs"
)


def strip_metadata(bytecode: bytes) -> bytes:
    """Trim trailing solc CBOR metadata (swarm/ipfs hash).

    The last two bytes encode the metadata length; verify it lands on a known
    marker before trimming (reference asm.py:94-140 trims by regex).
    """
    if len(bytecode) < 4:
        return bytecode
    meta_len = int.from_bytes(bytecode[-2:], "big")
    if 0 < meta_len <= len(bytecode) - 2:
        meta = bytecode[-(meta_len + 2) : -2]
        if _METADATA_RE.search(meta):
            return bytecode[: -(meta_len + 2)]
    return bytecode


def disassemble(bytecode: bytes) -> List[EvmInstruction]:
    """Linear sweep: bytecode -> [EvmInstruction]; unknown bytes -> INVALID."""
    instructions = []
    pc = 0
    n = len(bytecode)
    while pc < n:
        byte = bytecode[pc]
        name = BYTE_TO_NAME.get(byte)
        if name is None:
            instructions.append(EvmInstruction(pc, "INVALID"))
            pc += 1
            continue
        if name.startswith("PUSH") and name != "PUSH0":
            width = int(name[4:])
            arg = bytes(bytecode[pc + 1 : pc + 1 + width])
            arg = arg + b"\x00" * (width - len(arg))  # implicit zero padding at EOF
            instructions.append(EvmInstruction(pc, name, arg))
            pc += 1 + width
        else:
            instructions.append(EvmInstruction(pc, name))
            pc += 1
    return instructions


def find_op_code_sequence(pattern: List[List[str]], instruction_list) -> List[int]:
    """Indices where ``pattern`` (list of allowed-opcode lists) matches.

    Reference parity: mythril/disassembler/asm.py:60.
    """
    hits = []
    n = len(instruction_list)
    k = len(pattern)
    for i in range(n - k + 1):
        if all(instruction_list[i + j].opcode in pattern[j] for j in range(k)):
            hits.append(i)
    return hits


def _selector_dispatch_sites(instructions: List[EvmInstruction]) -> List[Tuple[int, int]]:
    """(selector, entry_pc) pairs from solc dispatcher patterns.

    Matches both the classic ``DUP1 PUSH4 sel EQ PUSHn dest JUMPI`` and the
    via-IR / optimizer variants where the DUP is elsewhere.
    """
    out = []
    pattern = [["PUSH4", "PUSH3", "PUSH2", "PUSH1"], ["EQ"], ["PUSH2", "PUSH1", "PUSH3"], ["JUMPI"]]
    for i in find_op_code_sequence(pattern, instructions):
        sel = instructions[i].arg_int
        dest = instructions[i + 2].arg_int
        out.append((sel, dest))
    # GT/LT-split dispatchers still end in the EQ pattern per function, so the
    # scan above covers them; also catch `PUSH4 sel DUP2 EQ PUSHn dest JUMPI`.
    pattern2 = [["PUSH4"], ["DUP2", "DUP1"], ["EQ"], ["PUSH2", "PUSH1", "PUSH3"], ["JUMPI"]]
    for i in find_op_code_sequence(pattern2, instructions):
        sel = instructions[i].arg_int
        dest = instructions[i + 3].arg_int
        out.append((sel, dest))
    return out


class Disassembly:
    """Disassembly of one bytecode blob + recovered function entry points.

    Reference parity: mythril/disassembler/disassembly.py:9-115.
    """

    def __init__(self, code, enable_online_lookup: bool = False):
        if isinstance(code, str):
            code = bytes.fromhex(code[2:] if code.startswith("0x") else code)
        self.bytecode: bytes = bytes(code)
        stripped = strip_metadata(self.bytecode)
        self.instruction_list: List[EvmInstruction] = disassemble(stripped)
        self._index_by_address = {
            ins.address: i for i, ins in enumerate(self.instruction_list)
        }

        self.func_hashes: List[int] = []
        self.function_name_to_address: Dict[str, int] = {}
        self.address_to_function_name: Dict[int, str] = {}

        from mythril_tpu.support.signatures import SignatureDB

        sigdb = SignatureDB(enable_online_lookup=enable_online_lookup)
        for selector, dest in _selector_dispatch_sites(self.instruction_list):
            self.func_hashes.append(selector)
            names = sigdb.get(f"0x{selector:08x}")
            name = names[0] if names else f"_function_0x{selector:08x}"
            self.function_name_to_address[name] = dest
            self.address_to_function_name[dest] = name

    def get_easm(self) -> str:
        lines = []
        for ins in self.instruction_list:
            if ins.argument is not None:
                lines.append(f"{ins.address} {ins.opcode} 0x{ins.argument.hex()}")
            else:
                lines.append(f"{ins.address} {ins.opcode}")
        return "\n".join(lines) + "\n"

    def instruction_at(self, address: int) -> Optional[EvmInstruction]:
        i = self._index_by_address.get(address)
        return self.instruction_list[i] if i is not None else None

    def index_of_address(self, address: int) -> Optional[int]:
        return self._index_by_address.get(address)

    def __len__(self):
        return len(self.instruction_list)
