"""Carrier walker: replay device events through real host GlobalStates.

The device executes the pure-opcode flood; everything the analysis layer can
observe — detector pre/post hooks, plugin signals, transaction-end world
states, annotations (taint) — is reproduced here by advancing a *carrier*
``GlobalState`` through the recorded event stream of each path:

  * E_HOOK / E_TERMINAL events route through ``laser.execute_state`` — the
    exact code path the host engine uses (mythril_tpu/core/svm.py:274-373,
    reference mythril/laser/ethereum/svm.py:336-449) — so hooks, signal
    handling, potential-issue checks and open-state archiving behave
    identically;
  * E_FORK events fire the JUMPI pre-hooks and then apply the device's
    branch decision (the fork the host engine would have made via
    ``copy.copy``, reference instructions.py:791-823);
  * between events the carrier's stack is synthesized from decoded operand
    rows — detectors only inspect the operands of the hooked opcode.

Annotation (taint) parity: host taint lives on smt wrapper objects and
propagates through operators.  The walker binds the wrapper that a hook saw
(and possibly annotated) to the arena row of that op's result; decoding any
later row unions the annotations of every bound row in its dependency
closure — the same reachability the host's operator-level unions compute.
"""

from __future__ import annotations

import copy as _copy
import logging
from typing import Dict, List, Optional, Set

import numpy as np

from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier import taint
from mythril_tpu.frontier.arena import HostArena
from mythril_tpu.frontier.records import PathRecord
from mythril_tpu.plugins.signals import PluginSkipState

log = logging.getLogger(__name__)


def fork_branch_row(ev: np.ndarray, taken: bool) -> int:
    """Arena row of the constraint an E_FORK event appends, or -1.

    THE authoritative decoding of the fork payload (written by
    step.py's jumpi handler / batch phase): for a single decided branch
    (extra == -1) the appended condition sits at EV_OP0+2; for a granted
    fork the taken child appends EV_OP0+2 (cond) and the falling-through
    parent EV_OP0+3 (Not cond).  Used by the event replay below and by the
    engine's lineage reconstruction (engine._lineage_constraint_rows).
    """
    extra = int(ev[O.EV_EXTRA])
    if extra == -3:
        return -1  # taken branch with invalid dest: path died, no constraint
    if extra == -1 or taken:
        return int(ev[O.EV_OP0 + 2])
    return int(ev[O.EV_OP0 + 3])


class Walker:
    def __init__(self, lasers, arena: HostArena, tables, seeds: List):
        """``lasers`` and ``tables`` are PER-SEED lists (parallel to
        ``seeds``): a multi-code batch replays each path through the laser
        and dispatch tables of the analysis that seeded it, so a corpus-wide
        segment harvests into 17 independent analyses correctly.  A single
        laser / CodeTables is accepted for the single-contract case."""
        if not isinstance(lasers, (list, tuple)):
            lasers = [lasers] * len(seeds)
        if not isinstance(tables, (list, tuple)):
            tables = [tables] * len(seeds)
        self.lasers = list(lasers)
        self.arena = arena
        self.tables = list(tables)
        self.seeds = seeds  # list of seed GlobalStates (one per tx spawn)
        # device gas counters start at 0 per path; issues must report
        # seed-relative totals (carrier copies don't carry custom attrs)
        self.gas_base = [
            (s.mstate.min_gas_used, s.mstate.max_gas_used) for s in seeds
        ]
        # arena row -> wrapper bound at a hook site (annotation carrier).
        # Partitioned PER LASER: annotations only ever flow within one
        # analysis (wrapper objects never cross lasers on the host), and the
        # partition is what lets the sharded harvest executor replay
        # different lasers' paths concurrently — a worker's decode closure
        # is a pure function of its own laser's replay history, with no
        # cross-thread binds (or memo clears) to race.  Interned arena rows
        # shared across lasers (e.g. common constants) no longer leak one
        # analysis' annotations into another — that was a latent bug of the
        # shared table, not behavior to preserve.
        self._bind_ctx: Dict[int, tuple] = {}  # id(laser) -> (bound, memo)
        # optional park routing hook (frontier/pipeline.py): called as
        # park_sink(laser, rec, carrier, snap) for parked carriers; a True
        # return means the sink took ownership (e.g. queued the state for
        # device re-injection) and the work-list append is skipped.  This
        # decouples harvesting a park from injecting it back somewhere.
        self.park_sink = None

    def add_seed(self, laser, tables, carrier) -> int:
        """Register a new seed mid-run (pipeline re-injection): appends to
        every per-seed parallel list and returns the new seed index."""
        idx = len(self.seeds)
        self.seeds.append(carrier)
        self.lasers.append(laser)
        self.tables.append(tables)
        self.gas_base.append(
            (carrier.mstate.min_gas_used, carrier.mstate.max_gas_used)
        )
        return idx

    def laser_for(self, rec: PathRecord):
        return self.lasers[rec.seed_idx]

    def tables_for(self, rec: PathRecord):
        return self.tables[rec.seed_idx]

    # ------------------------------------------------------------------
    # decode with annotation closure
    # ------------------------------------------------------------------

    def _binding(self, seed_idx: int) -> tuple:
        """(bound, anno_memo) dicts for the laser that owns ``seed_idx``.
        setdefault keeps creation atomic under concurrent replay workers
        (distinct lasers race only on the outer dict, never on a context).
        A laser-less walker (decode-only use) shares one sentinel context."""
        key = (
            id(self.lasers[seed_idx]) if seed_idx < len(self.lasers) else -1
        )
        return self._bind_ctx.setdefault(key, ({}, {}))

    def _annos(self, row: int, seed_idx: int) -> frozenset:
        bound, anno_memo = self._binding(seed_idx)
        got = anno_memo.get(row)
        if got is not None:
            return got
        out: Set = set()
        mask = 0
        stack = [int(row)]
        seen = set()
        while stack:
            r = stack.pop()
            if r < 0 or r in seen:
                continue
            seen.add(r)
            w = bound.get(r)
            if w is not None:
                out.update(getattr(w, "annotations", ()))
            ar = self.arena
            mask |= int(ar.taint[r])
            for ch in (ar.a[r], ar.b[r], ar.c[r]):
                ch = int(ch)
                if ch >= 0 and ar._row_has_term_arg(r, ch):
                    stack.append(ch)
        if mask:
            # taint-source bits reachable in the closure synthesize the
            # annotations their post-hooks would have installed — those
            # hooks' opcodes ship no device events at all (frontier/taint.py)
            out.update(taint.annotations_for_mask(mask))
        result = frozenset(out)
        anno_memo[row] = result
        return result

    def decode_wrapped(self, row: int, seed_idx: int = 0):
        """Arena row -> smt wrapper (BitVec/Bool) with taint closure.

        ``seed_idx`` selects the binding context (per laser): replay-time
        decodes pass the record's seed; the default covers single-laser
        callers (tests, single-contract engines)."""
        from mythril_tpu.smt import BitVec, Bool
        from mythril_tpu.smt import terms as T

        row = int(row)
        bound, _memo = self._binding(seed_idx)
        got = bound.get(row)
        if got is not None:
            return got
        term = self.arena.decode(row)
        annos = self._annos(row, seed_idx)
        if term.sort is T.BOOL:
            return Bool(term, annotations=annos)
        return BitVec(term, annotations=annos)

    def bind(self, row: int, wrapper, seed_idx: int = 0) -> None:
        if row is None or row < 0:
            return
        bound, anno_memo = self._binding(seed_idx)
        bound[int(row)] = wrapper
        anno_memo.clear()

    # ------------------------------------------------------------------
    # carrier management
    # ------------------------------------------------------------------

    def root_carrier(self, rec: PathRecord):
        seed = self.seeds[rec.seed_idx]
        carrier = _copy.copy(seed)
        return carrier

    def materialize(self, rec: PathRecord) -> None:
        """Ensure rec.carrier exists (walking ancestors as needed)."""
        if rec.carrier is not None or rec.dead:
            return
        if rec.parent is None:
            rec.carrier = self.root_carrier(rec)
            return
        parent = rec.parent
        self.advance(parent, rec.fork_event_idx + 1)
        if rec.carrier is None and not rec.dead:
            if parent.dead:
                # a hook killed the parent before the fork replayed: the
                # whole subtree dies with it (host parity: the state was
                # dropped before the JUMPI executed); the child inherits
                # the parent's termination class (a hook prune that kills
                # the subtree counts each descendant under the same class)
                rec.dead = True
                if rec.term_class is None:
                    rec.term_class = parent.term_class
                return
            # parent advance should have installed it via the fork event
            raise RuntimeError("fork event did not produce the child carrier")

    def advance(self, rec: PathRecord, upto: int) -> None:
        """Process rec.events[rec.carrier_pos:upto) on the carrier."""
        if rec.dead:
            return
        self.materialize(rec)
        if rec.dead:
            return
        while rec.carrier_pos < min(upto, len(rec.events)):
            ev = rec.events[rec.carrier_pos]
            rec.carrier_pos += 1
            try:
                self._process_event(rec, ev)
            except Exception:
                # an event that cannot replay poisons everything downstream
                # of it — kill the subtree cleanly (children see parent.dead)
                # instead of leaving half-advanced state behind
                rec.dead = True
                rec.carrier = None
                raise
            if rec.dead:
                return

    # ------------------------------------------------------------------
    # event processing
    # ------------------------------------------------------------------

    def _set_stack_from_ops(self, carrier, ev, seed_idx: int) -> None:
        ops = [int(ev[O.EV_OP0 + j]) for j in range(7)]
        ops = [r for r in ops if r >= 0]
        # ops are in pop order: stack top is ops[0]
        carrier.mstate.stack[:] = [
            self.decode_wrapped(r, seed_idx) for r in reversed(ops)
        ]

    def _set_gas(self, carrier, seed_idx: int, gmin: int, gmax: int) -> None:
        base = self.gas_base[seed_idx]
        carrier.mstate.min_gas_used = base[0] + gmin
        carrier.mstate.max_gas_used = base[1] + gmax

    def _restore_memory(self, rec: PathRecord) -> None:
        """Write the device's word table into the carrier memory.

        Most MSTOREs ship no event (code.py: MSTORE left _ALWAYS_EVENT;
        the user_assertions panic gate suppresses hook events for concrete
        non-panic values), so carrier memory is rebuilt wholesale from the
        final snapshot — once per path instead of once per write.  Called
        before the terminal event replays (RETURN/REVERT read their
        payload from memory) and before a parked carrier resumes on the
        host engine."""
        final = rec.final
        if final is None or rec.carrier is None:
            return
        for addr, row in final.get("mem", ()):
            rec.carrier.mstate.memory.write_word_at(
                int(addr), self.decode_wrapped(int(row), rec.seed_idx)
            )

    def _process_event(self, rec: PathRecord, ev: np.ndarray) -> None:
        carrier = rec.carrier
        kind = int(ev[O.EV_KIND])
        pc = int(ev[O.EV_PC])
        carrier.mstate.pc = pc
        self._set_gas(carrier, rec.seed_idx, int(ev[O.EV_GMIN]), int(ev[O.EV_GMAX]))

        laser = self.laser_for(rec)
        if kind == O.E_TERMINAL:
            # the terminal instruction (RETURN/REVERT payload, LOG data)
            # reads carrier memory, which per-write replay no longer keeps
            # current — install the device word table first
            self._restore_memory(rec)
        if kind in (O.E_HOOK, O.E_TERMINAL):
            self._set_stack_from_ops(carrier, ev, rec.seed_idx)
            new_states, op_code = laser.execute_state(carrier)
            if laser.requires_statespace:
                laser.manage_cfg(op_code, new_states)
            if kind == O.E_TERMINAL and new_states:
                # an INNER transaction ended on device: the host terminal
                # handler resumed the caller frame(s) (svm._end_message_call
                # via the <op>_post resume) — they continue on the host work
                # list.  (Outermost ends return [] after archiving the open
                # world state.)
                laser.work_list.extend(new_states)
                rec.carrier = None
                return
            if not new_states:
                rec.dead = True  # terminal, exceptional, or skipped
                rec.carrier = None
                return
            rec.carrier = new_states[0]
            if len(new_states) > 1:
                # can only happen if a hooked op forked on host; the device
                # never lets that happen (JUMPI is E_FORK)
                log.warning("unexpected host fork during event replay")
            res = int(ev[O.EV_RES])
            if res >= 0 and rec.carrier.mstate.stack:
                self.bind(res, rec.carrier.mstate.stack[-1], rec.seed_idx)
            return

        if kind == O.E_FORK:
            names = self.tables_for(rec).opcode_names
            op_name = names[pc] if pc < len(names) else "JUMPI"
            dest_row = int(ev[O.EV_OP0 + 0])
            word_row = int(ev[O.EV_OP0 + 1])
            if word_row >= 0:
                carrier.mstate.stack[:] = [
                    self.decode_wrapped(word_row, rec.seed_idx),
                    self.decode_wrapped(dest_row, rec.seed_idx),
                ]
            else:
                carrier.mstate.stack[:] = []
            # JUMPI pre-hooks (detectors); a skip kills the whole subtree,
            # matching the host engine dropping the state pre-execution
            try:
                for hook in laser._pre_hooks.get(op_name, []):
                    hook(carrier)
            except PluginSkipState:
                rec.dead = True
                # termination attribution: a detector/static-pass hook
                # pruned the path (harvest stamps the class at commit)
                if rec.term_class is None:
                    rec.term_class = "staticpass_pruned"
                rec.carrier = None
                return

            extra = int(ev[O.EV_EXTRA])
            if extra == -3:  # taken branch with invalid dest: path dies
                rec.dead = True
                rec.carrier = None
                return
            if extra == -1:  # single-branch decision (concrete or fall-only)
                cons_row = fork_branch_row(ev, taken=True)
                condition = None
                if cons_row >= 0:
                    condition = self.decode_wrapped(cons_row, rec.seed_idx)
                    carrier.world_state.constraints.append(condition)
                carrier.mstate.pc = int(ev[O.EV_RES])  # decided next pc
                carrier.mstate.depth += 1
                self._branch_node(laser, carrier, condition)
                return
            # granted fork: extra = child slot; child record was linked at
            # harvest via children_by_event
            cond_row = fork_branch_row(ev, taken=True)
            ncond_row = fork_branch_row(ev, taken=False)
            child = rec.children_by_event.get(rec.carrier_pos - 1)
            if child is not None and not child.dead:
                child_carrier = _copy.copy(carrier)
                cond = self.decode_wrapped(cond_row, rec.seed_idx)
                child_carrier.world_state.constraints.append(cond)
                child_carrier.mstate.pc = int(ev[O.EV_OP0 + 4])
                child_carrier.mstate.depth += 1
                self._branch_node(laser, child_carrier, cond)
                child.carrier = child_carrier
            ncond = self.decode_wrapped(ncond_row, rec.seed_idx)
            carrier.world_state.constraints.append(ncond)
            carrier.mstate.pc = pc + 1
            carrier.mstate.depth += 1
            self._branch_node(laser, carrier, ncond)
            return

        log.warning("unknown event kind %d", kind)

    @staticmethod
    def _branch_node(laser, carrier, condition) -> None:
        """CFG node transition for a JUMPI branch: function-entry naming and
        statespace bookkeeping (mirrors svm.manage_cfg for JUMPI,
        reference mythril/laser/ethereum/svm.py:506-532)."""
        if not laser.requires_statespace:
            return
        from mythril_tpu.core.cfg import JumpType

        laser._new_node_state(carrier, JumpType.CONDITIONAL, condition)
        if carrier.node is not None:
            carrier.node.states.append(carrier)

    # ------------------------------------------------------------------
    # path completion
    # ------------------------------------------------------------------

    def finish(self, rec: PathRecord) -> None:
        """Path halted on device: drain events, then act on the halt kind.

        Split into ``replay`` (laser-local: event drain + park-carrier
        restore, safe to run concurrently for DIFFERENT lasers) and
        ``commit`` (cross-laser side effects: park routing through the
        shared ``park_sink``), so the sharded harvest executor can fan
        replays out per laser and serialize commits in slot order.  Calling
        ``finish`` is exactly ``replay`` then ``commit`` — the serial path
        and the oracle for parity tests."""
        self.replay(rec)
        self.commit(rec)

    def replay(self, rec: PathRecord) -> None:
        """Drain the record's events and restore a parked carrier's device
        state (pc/stack/gas/memory) — everything expensive, everything
        laser-local.  Terminal paths fully complete here: their E_TERMINAL
        event runs the terminal instruction through ``laser.execute_state``
        (transaction end -> open states / inner-call resumes), all appends
        landing on the owning laser's own lists."""
        self.advance(rec, len(rec.events))
        if rec.dead or rec.final is None:
            return
        halt = rec.final["halt"]
        if halt in (O.H_PARK, O.H_PENDING_FORK):
            carrier = rec.carrier
            if carrier is None:
                return
            snap = rec.final
            self._restore_memory(rec)
            carrier.mstate.pc = snap["pc"]
            carrier.mstate.stack[:] = [
                self.decode_wrapped(int(r), rec.seed_idx)
                for r in snap["stack"]
            ]
            self._set_gas(carrier, rec.seed_idx, snap["gas_min"], snap["gas_max"])
            carrier.mstate.depth = snap["depth"]
            carrier.mstate.memory_size = snap["mem_size"]
            if snap.get("semantic_park"):
                # the device provably cannot execute THIS instruction:
                # engine._mid_eligible keeps the state host-side until the
                # host engine advances it past the parking pc
                carrier._frontier_park_pc = snap["pc"]

    def commit(self, rec: PathRecord) -> None:
        """Route a replayed record's outcome: park-sink / work-list hand-off
        for parked carriers, no-ops for the rest.  The park sink is shared
        across lasers (pipeline re-injection queue), so the executor calls
        this on the main thread in slot order — queue order is bit-identical
        to the serial harvest."""
        if rec.dead or rec.final is None:
            return
        halt = rec.final["halt"]
        if halt in (O.H_STOP, O.H_RETURN, O.H_REVERT, O.H_SELFDESTRUCT,
                    O.H_INVALID):
            # the E_TERMINAL event already ran the terminal instruction via
            # execute_state (transaction end -> open states); nothing to do
            return
        if halt in (O.H_DEPTH, O.H_LOOP):
            return  # silently dropped, host strategy / loop-bound parity
        if halt in (O.H_PARK, O.H_PENDING_FORK):
            carrier = rec.carrier
            if carrier is None:
                return
            snap = rec.final
            sink = self.park_sink
            if sink is not None:
                try:
                    if sink(self.laser_for(rec), rec, carrier, snap):
                        return
                except Exception as e:  # pragma: no cover - defensive
                    log.warning("park sink failed: %s", e)
            self.laser_for(rec).work_list.append(carrier)
            return
        log.warning("unhandled halt kind %d", halt)
