"""Service integration: real TCP daemon, concurrent clients, and the
determinism contract — per-request issue sets bit-identical to solo
one-shot runs of the same contracts.  Slow-marked: runs real analyses."""

import threading
from pathlib import Path

import pytest

from mythril_tpu.service import (
    AnalysisOptions,
    AnalysisService,
    ServiceConfig,
    issue_digest,
)
from mythril_tpu.service.client import ServiceClient
from mythril_tpu.service.server import AnalysisServer

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parents[2]
KILL_SIMPLE_HEX = (
    REPO / "tests" / "testdata" / "inputs" / "kill_simple.bin-runtime"
).read_text().strip()
CLEAN_HEX = "0x60006000f3"

OPTS = AnalysisOptions(transaction_count=2, execution_timeout=60)


def _etherstore_hex() -> str:
    import sys

    sys.path.insert(0, str(REPO))
    try:
        from bench_contracts import etherstore_like
    finally:
        sys.path.pop(0)
    return etherstore_like().hex()


def _solo_digests(contracts):
    """Ground truth: each contract analyzed alone, one-shot style."""
    from mythril_tpu.analysis.cooperative import run_cooperative_batch
    from mythril_tpu.facade.warm import reset_analysis_scope
    from mythril_tpu.service.codehash import normalize_code

    out = {}
    for name, code in contracts:
        reset_analysis_scope()
        issues_by_name, errors, _states = run_cooperative_batch(
            [(name, normalize_code(code))],
            transaction_count=OPTS.transaction_count,
            execution_timeout=OPTS.execution_timeout,
            isolate_errors=False,
        )
        assert not errors, f"solo run of {name} failed: {errors}"
        out[name] = sorted(issue_digest(i) for i in issues_by_name[name])
    reset_analysis_scope()
    return out


@pytest.fixture
def scoped_args():
    from mythril_tpu.facade.warm import reset_analysis_scope
    from mythril_tpu.support.support_args import args

    saved = dict(vars(args))
    yield
    vars(args).clear()
    vars(args).update(saved)
    from mythril_tpu.querycache import configure as configure_query_cache

    configure_query_cache(
        enabled=getattr(args, "query_cache", True),
        cache_dir=getattr(args, "query_cache_dir", None),
    )
    reset_analysis_scope()


def test_concurrent_clients_bit_identical_to_solo(scoped_args):
    """N>=4 concurrent TCP clients (duplicates by construction) each get
    the solo one-shot issue set, streamed, with dedup and a clean drain."""
    from mythril_tpu.observability.metrics import get_registry
    from mythril_tpu.support.support_args import args

    # persistent counter: earlier tests (crash containment) legitimately
    # error requests, so assert no NEW errors rather than zero ever
    errors0 = get_registry().counter(
        "service.request_errors", persistent=True
    ).snapshot() or 0

    contracts = [
        ("kill", KILL_SIMPLE_HEX),
        ("etherstore", _etherstore_hex()),
        ("clean", CLEAN_HEX),
    ]

    # ground truth first, same engine configuration as the service
    args.frontier = False
    args.probe_backend = "host"
    args.transaction_count = OPTS.transaction_count
    args.execution_timeout = OPTS.execution_timeout
    solo = _solo_digests(contracts)
    assert [i[0] for i in solo["kill"]] == ["106"]
    assert solo["clean"] == []

    server = AnalysisServer(
        ServiceConfig(
            default_options=OPTS,
            max_batch_width=8,
            batch_window_s=0.3,
            frontier=False,
            probe=True,
            warmup=False,
        ),
        host="127.0.0.1",
        port=0,
    ).start()
    host, port = server.address
    # every contract submitted twice -> 6 clients, dedup by construction
    jobs = [
        (f"c{i}", name, code, "interactive" if i == 0 else "batch")
        for i, (name, code) in enumerate(contracts * 2)
    ]
    results = {}
    lock = threading.Lock()

    def _client(cid, name, code, tier):
        client = ServiceClient(host, port, timeout=600)
        events = list(
            client.submit_stream(code, name=cid, tier=tier)
        )
        with lock:
            results[cid] = (name, events)

    try:
        threads = [
            threading.Thread(target=_client, args=job, daemon=True)
            for job in jobs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert len(results) == len(jobs), "a client never completed"

        deduped_count = 0
        for cid, (name, events) in results.items():
            kinds = [e["event"] for e in events]
            assert kinds[0] == "accepted", (cid, kinds)
            assert kinds[-1] == "done", (cid, kinds)
            if events[0]["deduped"]:
                deduped_count += 1
            done = events[-1]
            digests = sorted(issue_digest(i) for i in done["issues"])
            # the determinism contract: shared batching, probes and
            # dedup must not change any request's issue set
            assert digests == solo[name], f"{cid} ({name}) diverged"
            # streamed issue events are exactly the authoritative set
            streamed = sorted(
                issue_digest(e) for e in events if e["event"] == "issue"
            )
            assert streamed == digests, (cid, name)
        assert deduped_count >= 3  # second submission of each contract

        stats = ServiceClient(host, port).stats()
        assert stats["service.dedup_hits"] >= 3
        assert stats["service.request_errors"] == errors0
    finally:
        assert server.stop(drain=True, timeout=120) is True


def test_server_ping_and_malformed_request(scoped_args):
    server = AnalysisServer(
        ServiceConfig(
            default_options=OPTS, frontier=False, probe=False, warmup=False
        ),
        host="127.0.0.1",
        port=0,
    ).start()
    host, port = server.address
    try:
        client = ServiceClient(host, port, timeout=30)
        assert client.ping() is True
        # an invalid submission is an error event, not a dead socket
        events = list(client.submit_stream("not-hex", name="bad"))
        assert events[-1]["event"] == "error"
        assert "hex" in events[-1]["error"]
        # and the blocking helper surfaces it as an exception
        with pytest.raises(RuntimeError, match="analysis failed"):
            client.submit("not-hex", name="bad2")
    finally:
        server.stop(drain=True, timeout=30)
