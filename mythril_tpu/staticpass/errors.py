"""Typed errors for the static pass.

Over-approximation invariants in ``mythril_tpu/staticpass`` MUST raise
these (never bare ``assert``): the pass gates what the engine executes
and what the detector loader registers, so an invariant stripped under
``python -O`` would silently turn a soundness bug into missed issues.
The repo-local ruff rule (``S101`` scoped to this package in
``pyproject.toml``) enforces the ban mechanically.

Every consumer of the pass treats an escaped :class:`StaticPassError`
as "no static information" (``summary_for_code`` catches and returns
None), so raising here degrades to the unpruned analysis — it never
takes the analysis down.
"""

from __future__ import annotations


class StaticPassError(Exception):
    """Base class: any failure inside the static pre-analysis."""


class StaticInvariantError(StaticPassError):
    """An over-approximation invariant the pass relies on was violated
    (e.g. a refined reachability mask wider than the base mask, or an
    edge-liveness array misaligned with the instruction tables).  Raised
    instead of ``assert`` so ``-O`` cannot strip the check."""


class DispatchRecoveryError(StaticPassError):
    """Selector-dispatch recovery hit an internal inconsistency.  The
    recoverer catches this itself and degrades to the whole-contract
    single function, so callers only ever see the degraded result."""


def invariant(condition: bool, message: str) -> None:
    """``assert`` replacement that survives ``python -O``."""
    if not condition:
        raise StaticInvariantError(message)
