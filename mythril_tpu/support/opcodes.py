"""EVM opcode table: byte value, stack arity, and static gas bounds.

Role parity with the reference's table (mythril/support/opcodes.py:16-144):
maps mnemonic -> (byte, #stack-inputs, #stack-outputs, min_gas, max_gas).
Dynamic gas components (memory expansion, sha3 words, call stipends, ...) are
added by the interpreter via instruction_data.get_opcode_gas.

Covers the Shanghai instruction set (incl. PUSH0, BASEFEE, PREVRANDAO).
"""

from __future__ import annotations

from typing import Dict, Tuple

# mnemonic: (opcode byte, stack inputs, stack outputs, gas_min, gas_max)
OPCODES: Dict[str, Tuple[int, int, int, int, int]] = {}


def _op(name, byte, ins, outs, gmin, gmax=None):
    OPCODES[name] = (byte, ins, outs, gmin, gmax if gmax is not None else gmin)


_op("STOP", 0x00, 0, 0, 0)
_op("ADD", 0x01, 2, 1, 3)
_op("MUL", 0x02, 2, 1, 5)
_op("SUB", 0x03, 2, 1, 3)
_op("DIV", 0x04, 2, 1, 5)
_op("SDIV", 0x05, 2, 1, 5)
_op("MOD", 0x06, 2, 1, 5)
_op("SMOD", 0x07, 2, 1, 5)
_op("ADDMOD", 0x08, 3, 1, 8)
_op("MULMOD", 0x09, 3, 1, 8)
_op("EXP", 0x0A, 2, 1, 10, 10 + 50 * 32)
_op("SIGNEXTEND", 0x0B, 2, 1, 5)

_op("LT", 0x10, 2, 1, 3)
_op("GT", 0x11, 2, 1, 3)
_op("SLT", 0x12, 2, 1, 3)
_op("SGT", 0x13, 2, 1, 3)
_op("EQ", 0x14, 2, 1, 3)
_op("ISZERO", 0x15, 1, 1, 3)
_op("AND", 0x16, 2, 1, 3)
_op("OR", 0x17, 2, 1, 3)
_op("XOR", 0x18, 2, 1, 3)
_op("NOT", 0x19, 1, 1, 3)
_op("BYTE", 0x1A, 2, 1, 3)
_op("SHL", 0x1B, 2, 1, 3)
_op("SHR", 0x1C, 2, 1, 3)
_op("SAR", 0x1D, 2, 1, 3)

_op("SHA3", 0x20, 2, 1, 30, 30 + 6 * 8)
_op("KECCAK256", 0x20, 2, 1, 30, 30 + 6 * 8)

_op("ADDRESS", 0x30, 0, 1, 2)
_op("BALANCE", 0x31, 1, 1, 700)
_op("ORIGIN", 0x32, 0, 1, 2)
_op("CALLER", 0x33, 0, 1, 2)
_op("CALLVALUE", 0x34, 0, 1, 2)
_op("CALLDATALOAD", 0x35, 1, 1, 3)
_op("CALLDATASIZE", 0x36, 0, 1, 2)
_op("CALLDATACOPY", 0x37, 3, 0, 2, 2 + 3 * 768)
_op("CODESIZE", 0x38, 0, 1, 2)
_op("CODECOPY", 0x39, 3, 0, 2, 2 + 3 * 768)
_op("GASPRICE", 0x3A, 0, 1, 2)
_op("EXTCODESIZE", 0x3B, 1, 1, 700)
_op("EXTCODECOPY", 0x3C, 4, 0, 700, 700 + 3 * 768)
_op("RETURNDATASIZE", 0x3D, 0, 1, 2)
_op("RETURNDATACOPY", 0x3E, 3, 0, 3)
_op("EXTCODEHASH", 0x3F, 1, 1, 700)

_op("BLOCKHASH", 0x40, 1, 1, 20)
_op("COINBASE", 0x41, 0, 1, 2)
_op("TIMESTAMP", 0x42, 0, 1, 2)
_op("NUMBER", 0x43, 0, 1, 2)
_op("DIFFICULTY", 0x44, 0, 1, 2)
_op("PREVRANDAO", 0x44, 0, 1, 2)
_op("GASLIMIT", 0x45, 0, 1, 2)
_op("CHAINID", 0x46, 0, 1, 2)
_op("SELFBALANCE", 0x47, 0, 1, 5)
_op("BASEFEE", 0x48, 0, 1, 2)

_op("POP", 0x50, 1, 0, 2)
_op("MLOAD", 0x51, 1, 1, 3, 96)
_op("MSTORE", 0x52, 2, 0, 3, 98)
_op("MSTORE8", 0x53, 2, 0, 3, 98)
_op("SLOAD", 0x54, 1, 1, 800)
_op("SSTORE", 0x55, 2, 0, 5000, 25000)
_op("JUMP", 0x56, 1, 0, 8)
_op("JUMPI", 0x57, 2, 0, 10)
_op("PC", 0x58, 0, 1, 2)
_op("MSIZE", 0x59, 0, 1, 2)
_op("GAS", 0x5A, 0, 1, 2)
_op("JUMPDEST", 0x5B, 0, 0, 1)
_op("PUSH0", 0x5F, 0, 1, 2)

for _n in range(1, 33):
    _op(f"PUSH{_n}", 0x5F + _n, 0, 1, 3)
for _n in range(1, 17):
    _op(f"DUP{_n}", 0x7F + _n, _n, _n + 1, 3)
for _n in range(1, 17):
    _op(f"SWAP{_n}", 0x8F + _n, _n + 1, _n + 1, 3)
for _n in range(0, 5):
    _op(f"LOG{_n}", 0xA0 + _n, _n + 2, 0, 375 + 375 * _n, 375 + 375 * _n + 8 * 32)

_op("CREATE", 0xF0, 3, 1, 32000, 32000)
_op("CALL", 0xF1, 7, 1, 700, 700 + 9000 + 25000)
_op("CALLCODE", 0xF2, 7, 1, 700, 700 + 9000 + 25000)
_op("RETURN", 0xF3, 2, 0, 0)
_op("DELEGATECALL", 0xF4, 6, 1, 700, 700 + 9000 + 25000)
_op("CREATE2", 0xF5, 4, 1, 32000, 32000 + 6 * 768)
_op("STATICCALL", 0xFA, 6, 1, 700, 700 + 9000 + 25000)
_op("REVERT", 0xFD, 2, 0, 0)
_op("INVALID", 0xFE, 0, 0, 0)
_op("SELFDESTRUCT", 0xFF, 1, 0, 5000, 30000 + 25000)

# byte -> mnemonic (PREVRANDAO/KECCAK256 aliases resolve to canonical names)
BYTE_TO_NAME: Dict[int, str] = {}
for _name, (_byte, *_rest) in OPCODES.items():
    if _name in ("PREVRANDAO", "KECCAK256"):
        continue
    BYTE_TO_NAME.setdefault(_byte, _name)


def opcode_byte(name: str) -> int:
    return OPCODES[name][0]


def stack_inputs(name: str) -> int:
    return OPCODES[name][1]


def stack_outputs(name: str) -> int:
    return OPCODES[name][2]


def gas_bounds(name: str) -> Tuple[int, int]:
    _, _, _, gmin, gmax = OPCODES[name]
    return gmin, gmax
