"""Concrete-vs-symbolic variable wrappers + the Call op record.

Reference parity: mythril/analysis/ops.py:9-93 and call_helpers.py:10.
"""

from __future__ import annotations

from enum import Enum

from mythril_tpu.smt import BitVec


class VarType(Enum):
    SYMBOLIC = 1
    CONCRETE = 2


class Variable:
    def __init__(self, val, var_type: VarType):
        self.val = val
        self.type = var_type

    def __str__(self):
        return str(self.val)


def get_variable(i) -> Variable:
    try:
        from mythril_tpu.core.util import get_concrete_int

        return Variable(get_concrete_int(i), VarType.CONCRETE)
    except TypeError:
        return Variable(i, VarType.SYMBOLIC)


class Op:
    def __init__(self, node, state, state_index):
        self.node = node
        self.state = state
        self.state_index = state_index


class Call(Op):
    def __init__(self, node, state, state_index, call_type, to, gas, value=None):
        super().__init__(node, state, state_index)
        self.to = to
        self.gas = gas
        self.type = call_type
        self.value = value if value is not None else Variable(0, VarType.CONCRETE)
