"""EtherThief: attacker can withdraw more ether than deposited (SWC-105).

Reference parity: mythril/analysis/module/modules/ether_thief.py:54-99 —
value-transferring CALL with every tx sent by the attacker and the attacker's
net balance strictly increased; parked as a PotentialIssue.
"""

from __future__ import annotations

from typing import List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import UNPROTECTED_ETHER_WITHDRAWAL
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core.transaction.symbolic import ACTORS
from mythril_tpu.core.transaction.transaction_models import ContractCreationTransaction
from mythril_tpu.smt import UGT, symbol_factory

DESCRIPTION = """
Search for cases where Ether can be withdrawn to a user-specified address.
An issue is reported if there is a valid end state where the attacker has sent ether to the contract
and can withdraw more than deposited.
"""


class EtherThief(DetectionModule):
    name = "Any sender can withdraw ETH from the contract account"
    swc_id = UNPROTECTED_ETHER_WITHDRAWAL
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL"]
    # staticpass: value exfiltration needs a CALL
    static_required_ops = frozenset({"CALL"})

    def _execute(self, state: GlobalState) -> None:
        if self._cache_key(state) in self.cache:
            return None
        self._analyze_state(state)
        return None

    def _analyze_state(self, state: GlobalState) -> None:
        instruction = state.get_current_instruction()
        stack = state.mstate.stack
        value = stack[-3]
        target = stack[-2]

        constraints = []
        for tx in state.world_state.transaction_sequence:
            if not isinstance(tx, ContractCreationTransaction):
                constraints.append(tx.caller == ACTORS.attacker)

        # attacker ends up strictly ahead: transferred value exceeds the sum
        # the attacker paid in across the sequence
        total_paid = symbol_factory.BitVecVal(0, 256)
        for tx in state.world_state.transaction_sequence:
            if not isinstance(tx, ContractCreationTransaction):
                total_paid = total_paid + tx.call_value
        constraints += [
            target == ACTORS.attacker,
            UGT(value, total_paid),
        ]

        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.node.function_name if state.node else "unknown",
            address=instruction["address"],
            swc_id=UNPROTECTED_ETHER_WITHDRAWAL,
            title="Unprotected Ether Withdrawal",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head="Any sender can withdraw Ether from the contract account.",
            description_tail=(
                "Arbitrary senders other than the contract creator can profitably "
                "extract Ether from the contract account. Verify the business logic "
                "carefully and make sure that appropriate security controls are in "
                "place to prevent unexpected loss of funds."
            ),
            detector=self,
            constraints=constraints,
        )
        get_potential_issues_annotation(state).potential_issues.append(potential_issue)


detector = EtherThief
