"""Concrete/symbolic conversion helpers (reference parity: laser/ethereum/util.py:36-176)."""

from __future__ import annotations

from typing import List, Optional, Union

from mythril_tpu.smt import BitVec, symbol_factory


def get_concrete_int(item: Union[int, BitVec]) -> int:
    """Int value of a concrete BitVec; TypeError if symbolic (reference util.py:89)."""
    if isinstance(item, int):
        return item
    if isinstance(item, BitVec):
        if item.value is None:
            raise TypeError("symbolic value where concrete value expected")
        return item.value
    raise TypeError(f"cannot convert {type(item)} to concrete int")


def get_instruction_index(instruction_list, address: int) -> Optional[int]:
    """Index of the instruction at byte ``address`` (reference util.py:36)."""
    for i, ins in enumerate(instruction_list):
        if ins.address == address:
            return i
    return None


def concrete_int_from_bytes(data: List, offset: int) -> int:
    word = data[offset : offset + 32]
    out = 0
    for b in word:
        v = b if isinstance(b, int) else b.value
        out = (out << 8) | (v or 0)
    out <<= 8 * (32 - len(word))
    return out


def extract_copy(destination, source: bytes, dest_offset: int, offset: int, size: int) -> None:
    for i in range(size):
        destination[dest_offset + i] = source[offset + i] if offset + i < len(source) else 0


def pretty_state(global_state) -> str:
    ms = global_state.mstate
    return f"pc={ms.pc} op={global_state.get_current_instruction()['opcode']} stack={ms.stack}"
