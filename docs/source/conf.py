"""Sphinx configuration for mythril-tpu (mirrors the reference docs tree
scope, /root/reference/docs/source/conf.py, rebuilt for this package)."""

import os
import sys

sys.path.insert(0, os.path.abspath("../.."))

project = "mythril-tpu"
author = "mythril-tpu contributors"
release = "0.5.0"

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
]

# jax and the native library are heavyweight/optional at doc-build time
autodoc_mock_imports = ["jax", "jaxlib"]

templates_path = []
exclude_patterns = []
html_theme = "alabaster"
