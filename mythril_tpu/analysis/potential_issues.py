"""Deferred issues: modules park constraints, the engine solves once per tx end.

Reference parity: mythril/analysis/potential_issues.py:82-126 — modules create
PotentialIssue records (no model yet) on a state annotation;
check_potential_issues solves each at transaction end, converting the solvable
ones into confirmed Issues with concrete transaction sequences.  The
annotation's search_importance (10 x #issues) steers beam search (:61-62).
"""

from __future__ import annotations

import logging
from typing import List

from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.core.state.annotation import StateAnnotation
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.exceptions import UnsatError

log = logging.getLogger(__name__)


class PotentialIssue:
    def __init__(
        self,
        contract: str,
        function_name: str,
        address: int,
        swc_id: str,
        title: str,
        bytecode,
        detector,
        severity: str = "Medium",
        description_head: str = "",
        description_tail: str = "",
        constraints=None,
    ):
        self.contract = contract
        self.function_name = function_name
        self.address = address
        self.swc_id = swc_id
        self.title = title
        self.bytecode = bytecode
        self.severity = severity
        self.description_head = description_head
        self.description_tail = description_tail
        self.detector = detector
        self.constraints = constraints or []


class PotentialIssuesAnnotation(StateAnnotation):
    def __init__(self):
        self.potential_issues: List[PotentialIssue] = []

    @property
    def search_importance(self) -> int:
        return 10 * len(self.potential_issues)

    def __copy__(self):
        # shared across forks on purpose: issues park once per program point
        return self


def get_potential_issues_annotation(global_state: GlobalState) -> PotentialIssuesAnnotation:
    for annotation in global_state.get_annotations(PotentialIssuesAnnotation):
        return annotation
    annotation = PotentialIssuesAnnotation()
    global_state.annotate(annotation)
    return annotation


def check_potential_issues(global_state: GlobalState) -> None:
    """Called by the engine at outermost transaction end (svm counterpart of
    reference svm.py:423).

    The sat/unsat GATE over all parked issues runs as ONE batched sweep
    first (the sets share the whole path prefix — union model replay and
    merged dispatch resolve most), so the per-issue exploit synthesis
    (model + input minimization) is paid only for the satisfiable ones."""
    annotation = get_potential_issues_annotation(global_state)
    unsolved: List[PotentialIssue] = []
    gate = [True] * len(annotation.potential_issues)
    if len(annotation.potential_issues) >= 2:
        from mythril_tpu.smt.solver import ProbeConfig, check_satisfiable_batch
        from mythril_tpu.support.support_args import args
        from mythril_tpu.support.time_handler import time_handler

        # the gate gets the SAME budget the full solve would (solver_timeout
        # clamped by remaining execution time, cf. support/model.py): a
        # cheaper gate would turn hard-but-satisfiable issues into silent
        # recall losses at the final transaction end
        budget_ms = min(
            args.solver_timeout,
            int(max(time_handler.time_remaining(), 0) * 1000) // 2 + 1,
        )
        path_raws = list(global_state.world_state.constraints.get_all_raw())
        gate = check_satisfiable_batch(
            [
                path_raws
                + [c.raw if hasattr(c, "raw") else c for c in p.constraints]
                for p in annotation.potential_issues
            ],
            ProbeConfig(
                max_rounds=args.probe_rounds,
                candidates_per_round=args.probe_candidates,
                timeout_ms=max(1, budget_ms),
                prune_critical=True,
            ),
        )
    for potential_issue, feasible in zip(annotation.potential_issues, gate):
        if not feasible:
            # an UNKNOWN here degrades exactly like a failed solve below:
            # the issue stays parked and is retried at a later tx end
            unsolved.append(potential_issue)
            continue
        try:
            transaction_sequence = get_transaction_sequence(
                global_state,
                global_state.world_state.constraints + potential_issue.constraints,
            )
        except UnsatError:
            unsolved.append(potential_issue)
            continue
        potential_issue.detector.cache.add(
            (potential_issue.address, get_bytecode_hash(potential_issue.bytecode))
        )
        potential_issue.detector.issues.append(
            Issue(
                contract=potential_issue.contract,
                function_name=potential_issue.function_name,
                address=potential_issue.address,
                title=potential_issue.title,
                bytecode=potential_issue.bytecode,
                swc_id=potential_issue.swc_id,
                gas_used=(
                    global_state.mstate.min_gas_used,
                    global_state.mstate.max_gas_used,
                ),
                description_head=potential_issue.description_head,
                description_tail=potential_issue.description_tail,
                severity=potential_issue.severity,
                transaction_sequence=transaction_sequence,
            )
        )
    annotation.potential_issues = unsolved


def get_bytecode_hash(bytecode) -> str:
    from mythril_tpu.support.support_utils import get_code_hash

    return get_code_hash(bytecode) if bytecode is not None else ""
