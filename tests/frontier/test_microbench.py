"""Device-only efficiency microbench (engine._run_microbench).

The chained-dispatch subtraction isolates pure segment compute from the
host<->device link, so the per-chip instructions/sec number is measurable
even over a high-RTT tunnel.  Runs once per process on the first productive
segment when args.frontier_microbench is set.
"""

from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.frontier.stats import FrontierStatistics
from mythril_tpu.support.support_args import args as global_args


def _wide_contract(n_branches: int) -> bytes:
    out = b""
    for k in range(n_branches):
        dest = len(out) + 10
        out += bytes([0x60, k, 0x35, 0x60, 0x01, 0x16,
                      0x61, (dest >> 8) & 0xFF, dest & 0xFF, 0x57, 0x5B])
    return out + bytes([0x33, 0xFF])


def test_microbench_records_device_compute():
    old = (
        global_args.frontier,
        global_args.frontier_force,
        global_args.frontier_width,
        global_args.frontier_mesh,
        global_args.frontier_microbench,
    )
    global_args.frontier = True
    global_args.frontier_force = True
    global_args.frontier_width = 64
    global_args.frontier_mesh = False  # single-device path (mesh skips it)
    global_args.frontier_microbench = True
    reset_callback_modules()
    FrontierStatistics().reset()
    try:
        sym = SymExecWrapper(
            _wide_contract(6),
            address=0x0901D12E,
            strategy="bfs",
            transaction_count=1,
            execution_timeout=120,
            modules=["AccidentallyKillable"],
        )
        issues = fire_lasers(sym, white_list=["AccidentallyKillable"])
        assert any(i.swc_id == "106" for i in issues)
        mb = FrontierStatistics().microbench
        assert mb, "microbench never recorded"
        assert mb["segment_compute_s"] > 0
        assert mb["instructions_per_s"] > 0
        assert mb["n_exec_per_segment"] > 0
        assert mb["bytes_pushed_per_segment"] > 0
        assert mb["width"] == 64
        # it must also surface through the stats dict (report meta channel)
        assert FrontierStatistics().as_dict()["microbench"] == mb
    finally:
        (
            global_args.frontier,
            global_args.frontier_force,
            global_args.frontier_width,
            global_args.frontier_mesh,
            global_args.frontier_microbench,
        ) = old