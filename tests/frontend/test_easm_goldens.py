"""Disassembly conformance against the reference's golden .easm outputs.

The reference mount ships 13 expected disassembly listings
(/root/reference/tests/testdata/outputs_expected/*.sol.o.easm, harness
/root/reference/tests/disassembler_test.py) — pure data fixtures that act as
a free oracle for bytecode -> listing formatting: one line per instruction,
``<decimal address> <OPCODE> [0x<push-arg-hex>]``.
"""

from pathlib import Path

import pytest

from mythril_tpu.frontend.disassembler import Disassembly

INPUTS = Path("/root/reference/tests/testdata/inputs")
EXPECTED = Path("/root/reference/tests/testdata/outputs_expected")

GOLDENS = sorted(EXPECTED.glob("*.sol.o.easm")) if EXPECTED.is_dir() else []

# The goldens predate the reference's own opcode-table rename: its current
# support/opcodes.py names 0xfe INVALID and 0xff SELFDESTRUCT, while the
# stored listings still say ASSERT_FAIL / SUICIDE.  Normalize the LEGACY
# tokens to the names both codebases use today (documented deviation, not a
# formatting difference).
_LEGACY_TOKENS = {" ASSERT_FAIL": " INVALID", " SUICIDE": " SELFDESTRUCT"}

# overflow.sol.o.easm was generated from a different compiler's output than
# the overflow.sol.o shipped in the same mount (golden opens `PUSH1 0x60`,
# 388 lines; the input disassembles to `PUSH1 0x80`, 347 lines) — the golden
# is stale against its own input, so byte comparison is meaningless.
_STALE_GOLDENS = {"overflow.sol.o.easm"}


def _normalize(text: str) -> str:
    for legacy, current in _LEGACY_TOKENS.items():
        text = text.replace(legacy, current)
    return text


@pytest.mark.skipif(not GOLDENS, reason="reference goldens not mounted")
@pytest.mark.parametrize("golden", GOLDENS, ids=lambda p: p.name)
def test_easm_matches_reference_golden(golden):
    if golden.name in _STALE_GOLDENS:
        pytest.skip("golden predates the mounted input bytecode")
    source = INPUTS / golden.name[: -len(".easm")]
    if not source.exists():
        pytest.skip(f"no input for {golden.name}")
    code = source.read_text().strip()
    easm = Disassembly(code).get_easm()
    assert easm == _normalize(golden.read_text())
