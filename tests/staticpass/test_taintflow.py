"""Static taint reachability: forward closure + global-channel escalation."""

from mythril_tpu.frontend.disassembler import Disassembly
from mythril_tpu.frontier import taint
from mythril_tpu.staticpass.summary import summarize


def _summary(hexcode: str, is_creation: bool = False):
    code = bytes.fromhex(hexcode)
    return summarize(
        Disassembly(code).instruction_list,
        code_size=len(code),
        is_creation=is_creation,
    )


def test_source_reaches_downstream_sink():
    # ORIGIN; PUSH1 6; JUMPI; STOP; INVALID; JUMPDEST; STOP
    s = _summary("32600657" + "00" + "fe" + "5b00")
    assert "JUMPI" in s.taint_reach(taint.TAINT_ORIGIN)
    assert taint.TAINT_ORIGIN not in s.escalated_bits


def test_absent_source_reaches_nothing():
    s = _summary("32600657" + "00" + "fe" + "5b00")
    assert s.taint_reach(taint.TAINT_TIMESTAMP) == frozenset()


def test_sink_before_source_not_reached_without_channel():
    # PUSH1 1; PUSH1 7; JUMPI; STOP; INVALID; JUMPDEST(7); TIMESTAMP; POP; STOP
    # the only JUMPI executes strictly before TIMESTAMP and nothing global
    # carries the value backwards -> unreachable from the source
    s = _summary("6001600757" + "00" + "fe" + "5b425000")
    assert "JUMPI" not in s.taint_reach(taint.TAINT_TIMESTAMP)
    assert taint.TAINT_TIMESTAMP not in s.escalated_bits


def test_sstore_escalates_to_all_reachable_ops():
    # dispatch JUMPI first, then TIMESTAMP -> SSTORE: storage persists
    # across transactions, so the bit may reach EVERY reachable sink,
    # including the JUMPI that executed before the source this tx
    # PUSH1 1; PUSH1 7; JUMPI; STOP; INVALID; JUMPDEST(7); TIMESTAMP; PUSH1 0; SSTORE; STOP
    s = _summary("6001600757" + "00" + "fe" + "5b4260005500")
    assert taint.TAINT_TIMESTAMP in s.escalated_bits
    assert "JUMPI" in s.taint_reach(taint.TAINT_TIMESTAMP)


def test_call_family_escalates():
    # ORIGIN feeding a CALL: re-entry can run this code from pc 0 within
    # the influenced frame, so the bit escalates
    # ORIGIN; PUSH1 0 x5; GAS; CALL; STOP  (stack: gas to value in out inout)
    s = _summary("32" + "6000" * 5 + "5a" + "f1" + "00")
    assert taint.TAINT_ORIGIN in s.escalated_bits


def test_creation_code_treats_return_as_channel():
    # TIMESTAMP; PUSH1 0; MSTORE; PUSH1 32; PUSH1 0; RETURN — in creation
    # code the returned bytes BECOME the runtime code: channel hit
    code = "42600052" + "60206000f3"
    s_runtime = _summary(code)
    s_creation = _summary(code, is_creation=True)
    assert taint.TAINT_TIMESTAMP not in s_runtime.escalated_bits
    assert taint.TAINT_TIMESTAMP in s_creation.escalated_bits


def test_unreachable_source_reaches_nothing():
    # PUSH1 4; JUMP; ORIGIN(dead); JUMPDEST; STOP — the ORIGIN sits in the
    # statically dead pad, so its bit has no reachable source instruction
    s = _summary("600456" + "32" + "5b00")
    assert s.taint_reach(taint.TAINT_ORIGIN) == frozenset()
