"""Call-frame environment (reference parity: laser/ethereum/state/environment.py:12-82)."""

from __future__ import annotations

from typing import Optional

from mythril_tpu.core.state.account import Account
from mythril_tpu.core.state.calldata import BaseCalldata
from mythril_tpu.smt import BitVec, symbol_factory


class Environment:
    def __init__(
        self,
        active_account: Account,
        sender: BitVec,
        calldata: BaseCalldata,
        gasprice: BitVec,
        callvalue: BitVec,
        origin: BitVec,
        code=None,
        basefee: Optional[BitVec] = None,
        static: bool = False,
    ):
        self.active_account = active_account
        self.address = active_account.address
        self.sender = sender
        self.calldata = calldata
        self.gasprice = gasprice
        self.callvalue = callvalue
        self.origin = origin
        self.basefee = (
            basefee if basefee is not None else symbol_factory.BitVecSym("basefee", 256)
        )
        self.code = code if code is not None else active_account.code
        self.static = static
        # fresh per-environment symbols (reference environment.py:47-48)
        self.block_number = symbol_factory.BitVecSym("block_number", 256)
        self.chainid = symbol_factory.BitVecSym("chain_id", 256)
        # optional CONCRETE block-env overrides (None -> fresh symbols at the
        # opcode): set by the conformance/concolic drivers replaying fixtures
        # with known block parameters (VMTests ``env`` section)
        self.timestamp: Optional[BitVec] = None
        self.coinbase: Optional[BitVec] = None
        self.difficulty: Optional[BitVec] = None
        self.block_gaslimit: Optional[BitVec] = None

    def __copy__(self) -> "Environment":
        out = Environment.__new__(Environment)
        out.__dict__.update(self.__dict__)
        return out

    def __str__(self):
        return f"Environment(account={self.active_account.contract_name})"
