"""Annotation persisting confirmed issues across world states / calls.

Reference parity: mythril/analysis/issue_annotation.py:9-34.
"""

from __future__ import annotations

from typing import List

from mythril_tpu.analysis.report import Issue
from mythril_tpu.core.state.annotation import StateAnnotation
from mythril_tpu.smt import Bool


class IssueAnnotation(StateAnnotation):
    def __init__(self, conditions: List[Bool], issue: Issue, detector):
        self.conditions = conditions
        self.issue = issue
        self.detector = detector

    @property
    def persist_to_world_state(self) -> bool:
        return True

    @property
    def persist_over_calls(self) -> bool:
        return True

    def persist_to_world_state_annotation(self) -> bool:
        return True

    def __copy__(self):
        return self
