"""Batched DPLL search kernel over a packed CNF plane — host twin.

One step of the search is a pure, fully-vectorized function over integer
arrays with a query axis ``[Q, ...]`` (``_step``): a unit-propagation
sweep over every clause, contradiction detection, a single decision or a
chronological backtrack per query.  The host driver below runs the step
in a numpy ``while`` loop; ``devsolver/device.py`` runs the *same* step
function under ``lax.while_loop`` with ``xp = jax.numpy`` — the two are
bit-identical by construction (pure integer arithmetic, no floats, and
the only scatter is an order-independent logical-or), mirroring the
``absdomain/domains.py`` / ``absdomain/device.py`` pair.

CNF plane layout (built by ``devsolver/blaster.py``):

* every clause has at most 3 literals (the blaster emits only binary
  Tseitin gates plus unit assertions); a literal is ``2*var`` (positive)
  or ``2*var + 1`` (negated);
* variable 0 is the constant-FALSE anchor and variable 1 the
  constant-TRUE anchor: literal 0 (var 0, positive) pads unused literal
  slots (always false, never satisfies and never counts as unassigned),
  and clause ``[2]`` (var 1, positive) pads unused clause slots (always
  satisfied, never conflicts);
* decision variables are the *free input bits* of the blasted query in
  tape order.  Tseitin gate variables are propagation-complete once
  their gate inputs are assigned, so restricting DPLL splitting to the
  input bits loses no completeness; a fixed decision order means the
  decision stack is always a prefix of ``dec`` and backtracking needs no
  explicit trail.

Status codes per query: 0 = running, 1 = SAT (every clause has a true
literal; the partial assignment extends to a total model by setting the
remaining variables arbitrarily), 2 = UNSAT (conflict with no unflipped
decision below it), 3 = UNKNOWN (iteration budget exhausted, or the
defensive decisions-exhausted case that propagation completeness rules
out).  UNKNOWN always falls through to the exact tiers — the kernel can
never make the pipeline unsound, only undecided.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

__all__ = ["Plane", "pack_plane", "run_host", "RUNNING", "SAT_Q",
           "UNSAT_Q", "UNKNOWN_Q"]

RUNNING, SAT_Q, UNSAT_Q, UNKNOWN_Q = 0, 1, 2, 3

# (query, clause, variable) padding buckets; decision depth is fixed by
# the admission bit budget (devsolver_bit_budget <= MAX_DECISIONS)
Q_BUCKETS = (4, 16)
C_BUCKETS = (512, 4096)
V_BUCKETS = (512, 4096)
MAX_DECISIONS = 64


class Plane:
    """One padded CNF batch ready for the search kernel."""

    __slots__ = ("lits", "dec", "n_q", "n_vars")

    def __init__(self, lits: np.ndarray, dec: np.ndarray, n_q: int,
                 n_vars: int):
        self.lits = lits      # int32 [Q, C, 3]
        self.dec = dec        # int32 [Q, D], padded with var 1
        self.n_q = n_q        # real query count (rows beyond are padding)
        self.n_vars = n_vars  # padded variable count (anchors included)


def _bucket(v: int, buckets) -> int:
    for b in buckets:
        if v <= b:
            return b
    return buckets[-1]


def pack_plane(queries: Sequence[Tuple[List[List[int]], List[int]]],
               n_vars: int) -> Plane:
    """Pad per-query (clauses, decision_vars) into one plane.

    ``n_vars`` is the maximum variable count across the batch (anchor
    variables 0/1 included).  Clause/variable counts are padded to the
    shared buckets so the device twin compiles one program per bucket.
    """
    n_q = len(queries)
    if n_q > Q_BUCKETS[-1]:
        raise ValueError(
            "pack_plane: %d queries exceed the largest query bucket %d — "
            "chunk the batch" % (n_q, Q_BUCKETS[-1]))
    qb = _bucket(n_q, Q_BUCKETS)
    cb = _bucket(max((len(c) for c, _d in queries), default=1), C_BUCKETS)
    vb = _bucket(n_vars, V_BUCKETS)
    lits = np.zeros((qb, cb, 3), np.int32)
    lits[:, :, 0] = 2  # var-1-positive pad: every clause satisfied
    dec = np.ones((qb, MAX_DECISIONS), np.int32)  # var 1: skipped slots
    for qi, (clauses, dvars) in enumerate(queries):
        for ci, cl in enumerate(clauses):
            lits[qi, ci, : len(cl)] = cl
            lits[qi, ci, len(cl):] = 0  # var-0-positive: inert false
        for di, v in enumerate(dvars[:MAX_DECISIONS]):
            dec[qi, di] = v
    return Plane(lits, dec, n_q, vb)


def init_state(plane: Plane, xp=np):
    """(assign, level, dval, dflip, depth, status) initial arrays."""
    qb, _cb, _ = plane.lits.shape
    vb = plane.n_vars
    d = plane.dec.shape[1]
    assign = xp.zeros((qb, vb), xp.int8)
    # anchors: var 0 is constant false (2), var 1 constant true (1), both
    # at level 0 so no backtrack ever unassigns them
    assign = _set_col(xp, assign, 0, 2)
    assign = _set_col(xp, assign, 1, 1)
    level = xp.zeros((qb, vb), xp.int16)
    dval = xp.zeros((qb, d), xp.int8)
    dflip = xp.zeros((qb, d), xp.int8)
    depth = xp.zeros((qb,), xp.int32)
    status = xp.zeros((qb,), xp.int8)
    return assign, level, dval, dflip, depth, status


def _set_col(xp, a, col: int, val: int):
    if xp is np:
        a[:, col] = val
        return a
    return a.at[:, col].set(val)


def _scatter_or_np(shape, qi, vi, mask):
    out = np.zeros(shape, bool)
    np.logical_or.at(out, (qi, vi), mask)
    return out


def step(xp, scatter_or, lits, dec, assign, level, dval, dflip, depth,
         status):
    """One kernel step: propagate OR decide OR backtrack, per query.

    Pure integer function of its inputs — shared verbatim by the host
    numpy driver and the jitted device twin.
    """
    qb, cb, _k = lits.shape
    vb = assign.shape[1]
    d = dec.shape[1]
    running = status == RUNNING

    # --- clause sweep ------------------------------------------------
    v_idx = (lits >> 1).reshape(qb, cb * 3)
    neg = (lits & 1).astype(xp.int8)
    a = xp.take_along_axis(assign, v_idx, axis=1).reshape(qb, cb, 3)
    # literal truth: 0 unassigned, 1 true, 2 false
    cv = xp.where(neg == 1, xp.where(a == 0, 0, 3 - a), a)
    sat_c = (cv == 1).any(axis=2)
    n_un = (cv == 0).sum(axis=2)
    conflict_c = (~sat_c) & (n_un == 0)
    conflict_q = running & conflict_c.any(axis=1)

    # --- unit implications -------------------------------------------
    is_unit = (~sat_c) & (n_un == 1)
    unit_lit = xp.where(cv == 0, lits, 0).sum(axis=2)
    # non-unit clauses sum several literals, which can overflow the var
    # range: zero the index there (mask is False anyway).  numpy raises
    # on OOB scatter indices while XLA drops them — clamping keeps the
    # twins bit-identical AND crash-free.
    uv = xp.where(is_unit, unit_lit >> 1, 0).astype(xp.int32)
    qi = xp.broadcast_to(xp.arange(qb, dtype=xp.int32)[:, None], (qb, cb))
    imp_t = scatter_or((qb, vb), qi, uv, is_unit & ((unit_lit & 1) == 0))
    imp_f = scatter_or((qb, vb), qi, uv, is_unit & ((unit_lit & 1) == 1))
    # a variable implied both ways in one sweep is a conflict
    conflict_q = conflict_q | (running & (imp_t & imp_f).any(axis=1))

    apply_q = (running & ~conflict_q)[:, None]
    newly = apply_q & (assign == 0) & (imp_t ^ imp_f)
    assign = xp.where(newly & imp_t, xp.int8(1),
                      xp.where(newly & imp_f, xp.int8(2), assign))
    level = xp.where(newly, depth[:, None].astype(xp.int16), level)
    progressed = newly.any(axis=1)

    # --- fixpoint: SAT check or decide -------------------------------
    at_fix = running & ~conflict_q & ~progressed
    all_sat = sat_c.all(axis=1)
    status = xp.where(at_fix & all_sat, xp.int8(SAT_Q), status)

    need_dec = at_fix & ~all_sat
    exhausted = depth >= d
    status = xp.where(need_dec & exhausted, xp.int8(UNKNOWN_Q), status)
    nd = need_dec & ~exhausted
    d_clamp = xp.clip(depth, 0, d - 1)
    dv = xp.take_along_axis(dec, d_clamp[:, None], axis=1)[:, 0]
    dv_assigned = xp.take_along_axis(assign, dv[:, None], axis=1)[:, 0] != 0
    slot = xp.arange(d, dtype=xp.int32)[None, :] == d_clamp[:, None]
    var_hot = xp.arange(vb, dtype=xp.int32)[None, :] == dv[:, None]
    # skipped slot (variable already forced by propagation): mark it
    # tried-both so backtracking never flips a non-decision
    skip = nd & dv_assigned
    fresh = nd & ~dv_assigned
    dflip = xp.where(skip[:, None] & slot, xp.int8(1), dflip)
    # phase: try FALSE first (value 2) — engine conditions are
    # overwhelmingly "selector/counter equals small constant" shapes
    # whose models are zero-dominated
    dval = xp.where(fresh[:, None] & slot, xp.int8(2), dval)
    assign = xp.where(fresh[:, None] & var_hot, xp.int8(2), assign)
    level = xp.where(fresh[:, None] & var_hot,
                     (depth[:, None] + 1).astype(xp.int16), level)
    depth = xp.where(nd, depth + 1, depth)

    # --- backtrack ---------------------------------------------------
    cand = (xp.arange(d, dtype=xp.int32)[None, :] < depth[:, None]) & (
        dflip == 0)
    has = cand.any(axis=1)
    status = xp.where(conflict_q & ~has, xp.int8(UNSAT_Q), status)
    bt = conflict_q & has
    j = (d - 1) - xp.argmax(cand[:, ::-1].astype(xp.int8), axis=1).astype(
        xp.int32)
    keep = level <= j[:, None].astype(xp.int16)
    assign = xp.where(bt[:, None] & ~keep, xp.int8(0), assign)
    level = xp.where(bt[:, None] & ~keep, xp.int16(0), level)
    j_hot = xp.arange(d, dtype=xp.int32)[None, :] == j[:, None]
    dval = xp.where(bt[:, None] & j_hot, (3 - dval).astype(xp.int8), dval)
    nv = xp.where(j_hot, dval, xp.int8(0)).sum(axis=1).astype(xp.int8)
    jv = xp.take_along_axis(dec, xp.clip(j, 0, d - 1)[:, None],
                            axis=1)[:, 0]
    jv_hot = xp.arange(vb, dtype=xp.int32)[None, :] == jv[:, None]
    assign = xp.where(bt[:, None] & jv_hot, nv[:, None], assign)
    level = xp.where(bt[:, None] & jv_hot,
                     (j[:, None] + 1).astype(xp.int16), level)
    dflip = xp.where(bt[:, None] & j_hot, xp.int8(1),
                     xp.where(bt[:, None] & (
                         xp.arange(d, dtype=xp.int32)[None, :]
                         > j[:, None]), xp.int8(0), dflip))
    depth = xp.where(bt, j + 1, depth)

    return assign, level, dval, dflip, depth, status


def run_host(plane: Plane, max_iters: int
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Drive the shared step with numpy; returns (status[Q], assign[Q,V]).

    Queries still RUNNING when the iteration budget lapses are stamped
    UNKNOWN — identical to the device twin's post-loop stamping.
    """
    assign, level, dval, dflip, depth, status = init_state(plane)
    it = 0
    while it < max_iters and bool((status == RUNNING).any()):
        assign, level, dval, dflip, depth, status = step(
            np, _scatter_or_np, plane.lits, plane.dec, assign, level,
            dval, dflip, depth, status)
        it += 1
    status = np.where(status == RUNNING, np.int8(UNKNOWN_Q), status)
    return status, assign
