"""Admission control invariants: dedup, options grouping, interactive
priority, result replay, error non-caching — all exercised without
running any analysis (requests carry synthetic codehashes)."""

import pytest

from mythril_tpu.service.admission import AdmissionController
from mythril_tpu.service.request import AnalysisOptions, AnalysisRequest

OPTS = AnalysisOptions(transaction_count=1)
OPTS_3TX = AnalysisOptions(transaction_count=3)


def _req(rid, codehash="0x" + "ab" * 32, options=OPTS, tier="batch"):
    return AnalysisRequest(
        request_id=rid,
        name=rid,
        code=b"\x00",
        codehash=codehash,
        options=options,
        tier=tier,
    )


@pytest.fixture
def ctl():
    return AdmissionController(result_cache_size=4)


def test_first_submission_is_not_deduped(ctl):
    _stream, deduped = ctl.submit(_req("r1"))
    assert deduped is False
    assert ctl.depths()["service.queue_depth"] == 1


def test_duplicate_subscribes_to_pending_flight(ctl):
    ctl.submit(_req("r1"))
    _stream, deduped = ctl.submit(_req("r2"))
    assert deduped is True
    # one flight, two subscribers — not two queue entries
    assert ctl.depths()["service.queue_depth"] == 1
    [flight] = ctl.next_batch(max_width=4)
    assert [r.request_id for r in flight.requests] == ["r1", "r2"]


def test_duplicate_subscribes_to_running_flight(ctl):
    ctl.submit(_req("r1"))
    [flight] = ctl.next_batch(max_width=4)
    _stream, deduped = ctl.submit(_req("r2"))
    assert deduped is True
    assert ctl.depths() == {
        "service.queue_depth": 0,
        "service.inflight": 1,
        "service.result_cache": 0,
    }
    assert flight.requests[-1].request_id == "r2"


def test_same_code_different_options_is_a_new_flight(ctl):
    ctl.submit(_req("r1", options=OPTS))
    _stream, deduped = ctl.submit(_req("r2", options=OPTS_3TX))
    assert deduped is False
    assert ctl.depths()["service.queue_depth"] == 2


def test_next_batch_groups_one_options_key(ctl):
    ctl.submit(_req("r1", codehash="0x" + "01" * 32, options=OPTS))
    ctl.submit(_req("r2", codehash="0x" + "02" * 32, options=OPTS_3TX))
    ctl.submit(_req("r3", codehash="0x" + "03" * 32, options=OPTS))
    batch = ctl.next_batch(max_width=4)
    # anchor r1 (oldest) pulls r3 (same options); r2 stays pending
    assert [f.requests[0].request_id for f in batch] == ["r1", "r3"]
    assert ctl.depths()["service.queue_depth"] == 1
    assert [f.requests[0].request_id for f in ctl.next_batch(4)] == ["r2"]


def test_next_batch_respects_max_width(ctl):
    for i in range(5):
        ctl.submit(_req(f"r{i}", codehash=f"0x{i:064x}"))
    assert len(ctl.next_batch(max_width=3)) == 3
    assert len(ctl.next_batch(max_width=3)) == 2


def test_interactive_anchor_jumps_the_queue(ctl):
    ctl.submit(_req("r1", codehash="0x" + "01" * 32, options=OPTS))
    ctl.submit(
        _req("r2", codehash="0x" + "02" * 32, options=OPTS_3TX,
             tier="interactive")
    )
    assert ctl.has_interactive_pending()
    batch = ctl.next_batch(max_width=4)
    # the interactive flight anchors the batch even though r1 is older,
    # and r1 (different options) cannot ride along
    assert [f.requests[0].request_id for f in batch] == ["r2"]
    assert not ctl.has_interactive_pending()


def test_interactive_duplicate_upgrades_flight_tier(ctl):
    ctl.submit(_req("r1"))
    ctl.submit(_req("r2", tier="interactive"))
    assert ctl.has_interactive_pending()


def test_done_result_is_replayed_from_cache(ctl):
    stream, _ = ctl.submit(_req("r1"))
    [flight] = ctl.next_batch(max_width=1)
    flight.emit("issue", {"swc_id": "106"})
    flight.emit("done", {"issues": [{"swc_id": "106"}]})
    ctl.finish(flight)
    assert ctl.depths()["service.result_cache"] == 1

    replay, deduped = ctl.submit(_req("r2"))
    assert deduped is True
    events = list(replay.events(timeout=1))
    assert [k for k, _ in events] == ["issue", "done"]
    # replay never enqueues new work
    assert ctl.depths()["service.queue_depth"] == 0


def test_error_results_are_not_cached(ctl):
    ctl.submit(_req("r1"))
    [flight] = ctl.next_batch(max_width=1)
    flight.emit("error", "solver exploded")
    ctl.finish(flight)
    assert ctl.depths()["service.result_cache"] == 0
    # the same contract re-analyzes instead of replaying the failure
    _stream, deduped = ctl.submit(_req("r2"))
    assert deduped is False


def test_result_cache_is_bounded_lru(ctl):
    for i in range(6):  # cache size is 4
        ctl.submit(_req(f"r{i}", codehash=f"0x{i:064x}"))
        [flight] = ctl.next_batch(max_width=1)
        flight.emit("done", {"issues": []})
        ctl.finish(flight)
    assert ctl.depths()["service.result_cache"] == 4
    # oldest entries evicted: hash 0 re-analyzes, hash 5 replays
    assert ctl.submit(_req("x0", codehash=f"0x{0:064x}"))[1] is False
    assert ctl.submit(_req("x5", codehash=f"0x{5:064x}"))[1] is True


def test_drain_wait(ctl):
    assert ctl.drain_wait(timeout=0.1) is True
    ctl.submit(_req("r1"))
    assert ctl.drain_wait(timeout=0.1) is False
    [flight] = ctl.next_batch(max_width=1)
    assert ctl.drain_wait(timeout=0.1) is False
    flight.emit("done", {"issues": []})
    ctl.finish(flight)
    assert ctl.drain_wait(timeout=0.1) is True


def test_dedup_counters_increment():
    from mythril_tpu.observability.metrics import get_registry

    reg = get_registry()
    before_dedup = reg.counter("service.dedup_hits", persistent=True).snapshot()
    before_replay = reg.counter("service.replay_hits", persistent=True).snapshot()

    ctl = AdmissionController()
    ctl.submit(_req("r1"))
    ctl.submit(_req("r2"))  # in-flight dedup
    [flight] = ctl.next_batch(max_width=1)
    flight.emit("done", {"issues": []})
    ctl.finish(flight)
    ctl.submit(_req("r3"))  # replay dedup

    assert (
        reg.counter("service.dedup_hits", persistent=True).snapshot()
        - before_dedup
    ) == 2
    assert (
        reg.counter("service.replay_hits", persistent=True).snapshot()
        - before_replay
    ) == 1
