"""CALL-family parameter plumbing: pop args, resolve callee, build calldata.

Reference parity: mythril/laser/ethereum/call.py:31-258 — including the
``Storage[n]`` regex trick for resolving callee addresses stored in storage
via the dynamic loader (reference :103-115) and precompile routing (:207-258).
"""

from __future__ import annotations

import logging
import re
from typing import List, Optional, Tuple, Union

from mythril_tpu.core.state.calldata import (
    BaseCalldata,
    ConcreteCalldata,
    SymbolicCalldata,
)
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core import natives
from mythril_tpu.core.instruction_data import calculate_native_gas
from mythril_tpu.smt import BitVec, symbol_factory

log = logging.getLogger(__name__)

SYMBOLIC_CALLDATA_SIZE = 320  # reference call.py:31

PRECOMPILE_COUNT = len(natives.PRECOMPILE_FUNCTIONS)


class SymbolicCalleeError(Exception):
    """Callee address cannot be resolved to anything executable."""


def get_call_output_location(global_state: GlobalState, op_code: str):
    """Peek (not pop) the ret-out memory window operands."""
    stack = global_state.mstate.stack
    if op_code in ("CALL", "CALLCODE"):
        return stack[-6], stack[-7]
    return stack[-5], stack[-6]


def get_call_parameters(
    global_state: GlobalState, dynamic_loader, with_value: bool = False
):
    """Pop and resolve all CALL-family operands.

    Returns (callee_address, callee_account, call_data, value, gas,
    memory_out_offset, memory_out_size).
    """
    stack = global_state.mstate.stack
    gas = stack.pop()
    to = stack.pop()
    value = stack.pop() if with_value else symbol_factory.BitVecVal(0, 256)
    memory_input_offset = stack.pop()
    memory_input_size = stack.pop()
    memory_out_offset = stack.pop()
    memory_out_size = stack.pop()

    callee_address = get_callee_address(global_state, dynamic_loader, to)
    callee_account = None
    call_data = get_call_data(global_state, memory_input_offset, memory_input_size)

    if isinstance(callee_address, BitVec) and callee_address.value is None:
        # fully symbolic callee — caller decides how to model it
        raise SymbolicCalleeError()

    addr_int = (
        callee_address.value
        if isinstance(callee_address, BitVec)
        else int(callee_address, 16)
    )
    if not (0 < addr_int <= PRECOMPILE_COUNT):
        callee_account = global_state.world_state.accounts_exist_or_load(
            addr_int, dynamic_loader
        )
    if isinstance(callee_address, str):
        callee_address = symbol_factory.BitVecVal(int(callee_address, 16), 256)
    return (
        callee_address,
        callee_account,
        call_data,
        value,
        gas,
        memory_out_offset,
        memory_out_size,
    )


def get_callee_address(global_state: GlobalState, dynamic_loader, symbolic_to_address):
    """Resolve the callee: concrete value, or a storage-slot load via RPC.

    Reference parity: call.py:83-126 — a symbolic address whose term is a
    storage read of the active account triggers a dynamic-loader lookup.
    """
    if symbolic_to_address.value is not None:
        return symbolic_to_address

    # match select(Storage[addr], <const idx>) terms
    raw = symbolic_to_address.raw
    if (
        raw.op == "select"
        and raw.args[0].op == "array_var"
        and raw.args[1].is_const
        and dynamic_loader is not None
        and getattr(dynamic_loader, "active", False)
    ):
        m = re.match(r"Storage\[0x([0-9a-f]+)\]", raw.args[0].aux or "")
        if m:
            contract_addr = f"0x{int(m.group(1), 16):040x}"
            try:
                slot = raw.args[1].value
                value = dynamic_loader.read_storage(contract_addr, slot)
                return "0x" + value[-40:].rjust(40, "0")
            except Exception:  # noqa: BLE001 — loader failure = unresolvable
                log.debug("dynamic callee resolution failed")
    return symbolic_to_address


def get_call_data(global_state: GlobalState, memory_start, memory_size) -> BaseCalldata:
    """Build the child tx's calldata view from caller memory (reference :151-205)."""
    mstate = global_state.mstate
    tx_id = f"{global_state.current_transaction.id}_internalcall"
    if memory_start.value is not None and memory_size.value is not None:
        size = min(memory_size.value, 0x10000)
        raw_bytes = mstate.memory.read_bytes(memory_start.value, size)
        if all(b.value is not None for b in raw_bytes):
            return ConcreteCalldata(tx_id, [b.value for b in raw_bytes])
        # symbolic bytes present: keep a basic concrete view over the terms
        from mythril_tpu.core.state.calldata import BasicConcreteCalldata

        class _TermCalldata(BaseCalldata):
            def __init__(self, tx_id_, data):
                super().__init__(tx_id_)
                self._data = data

            @property
            def size(self):
                return len(self._data)

            def _load(self, item):
                if isinstance(item, int):
                    return (
                        self._data[item]
                        if 0 <= item < len(self._data)
                        else symbol_factory.BitVecVal(0, 8)
                    )
                value = symbol_factory.BitVecVal(0, 8)
                from mythril_tpu.smt import If

                for i in range(len(self._data) - 1, -1, -1):
                    value = If(
                        item == symbol_factory.BitVecVal(i, 256), self._data[i], value
                    )
                return value

            def concrete(self, model):
                return [
                    b.value if b.value is not None else int(model.eval(b)) if model else 0
                    for b in self._data
                ]

        return _TermCalldata(tx_id, raw_bytes)
    log.debug("symbolic calldata window for inner call; using symbolic calldata")
    return SymbolicCalldata(tx_id)


def native_call(
    global_state: GlobalState,
    callee_address,
    call_data: BaseCalldata,
    memory_out_offset,
    memory_out_size,
) -> Optional[List[GlobalState]]:
    """Execute a precompile inline; None if the target is not a precompile.

    Reference parity: call.py:207-258 — symbolic input raises
    NativeContractException and degrades to fresh symbols in the out window.
    """
    if not isinstance(callee_address, BitVec) or callee_address.value is None:
        return None
    addr_int = callee_address.value
    if not (0 < addr_int <= PRECOMPILE_COUNT):
        return None

    contract_name = natives.PRECOMPILE_NAMES[addr_int - 1]
    instr = global_state.get_current_instruction()

    try:
        data = call_data.concrete(None)
        gmin, gmax = calculate_native_gas(len(data), contract_name)
        global_state.mstate.min_gas_used += gmin
        global_state.mstate.max_gas_used += gmax
        result_bytes = natives.native_contracts(addr_int, data)
        success = True
    except natives.NativeContractException:
        result_bytes = None
        success = False

    mem_out_start = memory_out_offset.value
    mem_out_size = memory_out_size.value if memory_out_size.value is not None else 32
    if result_bytes is not None and mem_out_start is not None:
        n = min(len(result_bytes), mem_out_size)
        for i in range(n):
            global_state.mstate.memory.set_byte(mem_out_start + i, result_bytes[i])
        global_state.last_return_data = bytes(result_bytes)
    elif mem_out_start is not None:
        # symbolic precompile input: fresh symbols in the out window
        for i in range(min(mem_out_size, 32)):
            global_state.mstate.memory.set_byte(
                mem_out_start + i,
                global_state.new_bitvec(f"{contract_name}_out_{instr['address']}_{i}", 8),
            )
        global_state.last_return_data = None

    ret = global_state.new_bitvec(f"retval_{instr['address']}", 256)
    global_state.mstate.stack.append(ret)
    global_state.world_state.constraints.append(
        ret == symbol_factory.BitVecVal(1 if success or result_bytes is None else 0, 256)
    )
    return [global_state]
