"""Benchmark plugin: coverage-over-time + executed-instruction counts.

Reference parity: mythril/laser/plugin/plugins/benchmark.py:19-94 (matplotlib
rendering replaced by a JSON dump — no display in this environment).
"""

from __future__ import annotations

import json
import logging
import time
from typing import List, Tuple

from mythril_tpu.plugins.interface import LaserPlugin, PluginBuilder

log = logging.getLogger(__name__)


class BenchmarkPlugin(LaserPlugin):
    def __init__(self, name: str = "benchmark"):
        self.nr_of_executed_insns = 0
        self.begin: float = 0.0
        self.end: float = 0.0
        self.points: List[Tuple[float, int]] = []
        self.name = name

    def initialize(self, symbolic_vm) -> None:
        self.begin = time.time()

        def execute_state_hook(_):
            self.nr_of_executed_insns += 1
            self.points.append((time.time() - self.begin, self.nr_of_executed_insns))

        def stop_hook():
            self.end = time.time()
            duration = self.end - self.begin
            rate = self.nr_of_executed_insns / duration if duration > 0 else 0.0
            log.info(
                "Benchmark: %d instructions in %.2fs (%.0f/s)",
                self.nr_of_executed_insns,
                duration,
                rate,
            )

        symbolic_vm.register_laser_hooks("execute_state", execute_state_hook)
        symbolic_vm.register_laser_hooks("stop_sym_exec", stop_hook)

    def write_to_file(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(
                {
                    "executed_instructions": self.nr_of_executed_insns,
                    "duration": self.end - self.begin,
                    "series": self.points[:10000],
                },
                f,
            )


class BenchmarkPluginBuilder(PluginBuilder):
    name = "benchmark"

    def __call__(self, *args, **kwargs) -> LaserPlugin:
        return BenchmarkPlugin()
