"""Abstract stack-height analysis: statically guaranteed underflows.

Per block, a fixpoint over the CFG propagates the MAXIMUM possible entry
stack height (join = max, capped at the EVM's 1024 limit).  If even that
maximum height underflows at some instruction, every path through the
block underflows there — the VM exceptionally halts, so the rest of the
block and its outgoing edges are statically dead.  Using the maximum is
what makes the proof sound: a lower real entry height only underflows
earlier.
"""

from __future__ import annotations

import numpy as np

from mythril_tpu.staticpass.cfg import StaticCFG

_EVM_STACK_LIMIT = 1024


def underflow_points(cfg: StaticCFG) -> np.ndarray:
    """Per block: instruction index of the first statically guaranteed
    stack underflow, or -1.  Only meaningful for reachable blocks."""
    t = cfg.tables
    B = cfg.n_blocks
    entry_max = np.full(B, -1, np.int64)  # -1 = not yet visited
    under = np.full(B, -1, np.int32)
    if not B:
        return under
    entry_max[0] = 0  # a frame always starts with an empty stack

    def walk(b: int):
        """(first_underflow_instr or -1, exit_height or None)."""
        cur = int(entry_max[b])
        for i in range(int(cfg.block_start[b]), int(cfg.block_end[b])):
            if cur < int(t.arity[i]):
                return i, None
            cur = min(cur + int(t.delta[i]), _EVM_STACK_LIMIT)
        return -1, cur

    worklist = [0]
    while worklist:
        b = worklist.pop()
        u, exit_h = walk(b)
        if u >= 0:
            continue  # no exit: successors get nothing from this block
        for nb in cfg.succ[b]:
            if exit_h > entry_max[nb]:
                entry_max[nb] = exit_h
                worklist.append(nb)

    # final verdicts with the converged (over-approximate) entry heights
    for b in range(B):
        if entry_max[b] >= 0:
            under[b], _ = walk(b)
    return under
