"""Concolic subsystem end-to-end: trace replay + JUMPI branch flipping.

Reference parity: the §3.5 flow (myth concolic input.json --branches N) —
replay a concrete transaction, then negate the path constraint at a chosen
JUMPI and solve for inputs that take the other branch.
"""

import json

# if (calldataload(0) == 5) storage[0] = 1 else storage[0] = 2
#   0: PUSH1 0; CALLDATALOAD; PUSH1 5; EQ; PUSH1 0x0f; JUMPI   <- pc 8
#   9: PUSH1 2; PUSH1 0; SSTORE; STOP
#  15: JUMPDEST; PUSH1 1; PUSH1 0; SSTORE; STOP
BRANCH_CODE = "600035600514600f576002600055005b600160005500"
JUMPI_ADDRESS = 8
CONTRACT = "0x" + "ab" * 20
CALLER = "0x" + "cd" * 20


def _concrete_data(input_hex: str) -> dict:
    return {
        "initialState": {
            "accounts": {
                CONTRACT: {
                    "balance": "0x0",
                    "code": "0x" + BRANCH_CODE,
                    "nonce": 0,
                    "storage": {},
                }
            }
        },
        "steps": [
            {
                "address": CONTRACT,
                "blockCoinbase": "0x" + "00" * 20,
                "blockDifficulty": "0x0",
                "blockGasLimit": "0x989680",
                "blockNumber": "0x1",
                "blockTime": "0x1",
                "gasLimit": "0x100000",
                "gasPrice": "0x0",
                "input": input_hex,
                "origin": CALLER,
                "value": "0x0",
            }
        ],
    }


def test_branch_flip_produces_input_for_other_side():
    from mythril_tpu.concolic.concolic_execution import concolic_execution

    # concrete run takes the != 5 branch; flipping the JUMPI must synthesize
    # calldata whose first word equals 5
    data = _concrete_data("0x" + "00" * 32)
    results = concolic_execution(data, [JUMPI_ADDRESS], solver_timeout=30000)
    assert len(results) == 1
    flipped_input = results[0]["steps"][0]["input"]
    word = int(flipped_input[2:66].ljust(64, "0"), 16)
    assert word == 5


def test_flip_from_taken_branch():
    from mythril_tpu.concolic.concolic_execution import concolic_execution

    # concrete run TAKES the jump (input word == 5); the flip must find a
    # word != 5
    data = _concrete_data("0x" + "00" * 31 + "05")
    results = concolic_execution(data, [JUMPI_ADDRESS], solver_timeout=30000)
    assert len(results) == 1
    flipped_input = results[0]["steps"][0]["input"]
    word = int(flipped_input[2:66].ljust(64, "0"), 16)
    assert word != 5


def test_concrete_execution_records_trace():
    from mythril_tpu.concolic.find_trace import concrete_execution

    init_state, trace = concrete_execution(_concrete_data("0x" + "00" * 32))
    pcs = [pc for pc, _tx in trace]
    # the fallthrough path executes the SSTORE at pc index 9..13 region
    assert len(pcs) > 5
