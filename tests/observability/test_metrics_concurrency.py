"""Concurrency: counters hammered from 8 threads must not lose updates.

The pipelined frontier's feasibility pool mutates solver and querycache
counters from worker threads; ``x += 1`` on a shared attribute is a lost
update waiting to happen, so the registry's mutators (Counter.inc,
Histogram.observe, LabeledCounter.inc) and the SolverStatistics facade's
``inc`` must be atomic.  Exact totals are asserted — a single lost
increment fails the test.
"""

import threading

from mythril_tpu.observability.metrics import get_registry

N_THREADS = 8
N_ITER = 2000


def _hammer(fn):
    barrier = threading.Barrier(N_THREADS)

    def run():
        barrier.wait()  # maximize interleaving
        for _ in range(N_ITER):
            fn()

    threads = [threading.Thread(target=run) for _ in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def test_counter_inc_is_atomic():
    reg = get_registry()
    c = reg.counter("test.concurrency.counter")
    c.reset()
    _hammer(lambda: c.inc())
    assert c.value == N_THREADS * N_ITER


def test_labeled_counter_inc_is_atomic():
    reg = get_registry()
    lc = reg.labeled_counter("test.concurrency.labeled")
    lc.reset()
    _hammer(lambda: lc.inc("x"))
    assert lc["x"] == N_THREADS * N_ITER


def test_histogram_observe_is_atomic():
    reg = get_registry()
    h = reg.histogram("test.concurrency.hist")
    h.reset()
    _hammer(lambda: h.observe(0.003))
    assert h.count == N_THREADS * N_ITER
    assert abs(h.sum - 0.003 * N_THREADS * N_ITER) < 1e-6
    assert sum(h.bucket_counts) == N_THREADS * N_ITER


def test_solver_statistics_inc_is_atomic():
    from mythril_tpu.smt.solver import SolverStatistics

    stats = SolverStatistics()
    stats.reset()
    _hammer(lambda: stats.inc("query_count"))
    _hammer(lambda: stats.inc("solver_time", 0.001))
    assert stats.query_count == N_THREADS * N_ITER
    assert abs(stats.solver_time - 0.001 * N_THREADS * N_ITER) < 1e-6


def test_querycache_counters_are_atomic():
    reg = get_registry()
    c = reg.counter("querycache.lookups")
    base = c.value
    _hammer(lambda: c.inc())
    assert c.value - base == N_THREADS * N_ITER


def test_snapshot_under_writer_storm():
    """snapshot() raced against 8 writers stays JSON-serializable and
    never observes torn metric state (the heartbeat sampler and the
    --metrics-out exporter both read while the pipeline writes)."""
    import json

    reg = get_registry()
    c = reg.counter("test.storm.counter")
    c.reset()
    h = reg.histogram("test.storm.hist")
    h.reset()
    lc = reg.labeled_counter("test.storm.labeled")
    lc.reset()
    g = reg.gauge("test.storm.gauge")

    stop = threading.Event()
    reader_errors = []

    def read_loop():
        try:
            while not stop.is_set():
                snap = reg.snapshot()
                json.dumps(snap)  # must serialize mid-storm
                v = snap.get("test.storm.counter", 0)
                assert 0 <= v <= N_THREADS * N_ITER
        except Exception as exc:  # pragma: no cover - failure path
            reader_errors.append(exc)

    reader = threading.Thread(target=read_loop)
    reader.start()

    def write():
        c.inc()
        h.observe(0.001)
        lc.inc("shard0")
        g.set({"shard0": 1, "shard1": 2})

    try:
        _hammer(write)
    finally:
        stop.set()
        reader.join()
    assert not reader_errors
    assert c.value == N_THREADS * N_ITER
    assert h.count == N_THREADS * N_ITER
    assert lc["shard0"] == N_THREADS * N_ITER
