"""Large-code frontier: per-code bucket isolation + packed-code paging.

One creation-heavy outlier used to inflate the corpus-wide
``multi_size_bucket`` so every small code paid the outlier's padded
instruction axis (the BENCH_r19 bectoken collapse).  Bucket classes give
each size cluster its own compiled segment; codes beyond the residency
budget keep only a hot window device-resident, and a cold jump faults to
the host (``H_PAGE_FAULT``) for a sync-point repack.  The contract under
test everywhere: the issue set is bit-identical with the optimization on
or off — a faulted path degrades to an ordinary host park, and the host
engine is always correct.
"""

from collections import namedtuple

import numpy as np
import pytest

from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier.arena import HostArena
from mythril_tpu.frontier.code import (
    CodeTables,
    _LOOPS_CAP,
    bucket_classes,
    bucket_hint_classes,
    multi_size_bucket,
    pad_waste_pct,
    page_budget,
    stacked_device_tables,
    visited_instr_cap,
)
from mythril_tpu.frontier.engine import FrontierEngine
from mythril_tpu.frontier.state import Caps, empty_state
from mythril_tpu.support.support_args import args

Ins = namedtuple("Ins", "opcode address arg_int")


def _program(n_pops: int, n_pushes: int = 0):
    """n_pushes PUSH1s then n_pops POPs (distinct families, so window
    slicing is observable in the fam table)."""
    out = []
    addr = 0
    for _ in range(n_pushes):
        out.append(Ins("PUSH1", addr, 0))
        addr += 2
    for _ in range(n_pops):
        out.append(Ins("POP", addr, None))
        addr += 1
    return out


@pytest.fixture
def paging_defaults():
    prev = (args.code_paging, args.code_page_budget)
    args.code_paging, args.code_page_budget = True, 2048
    yield
    args.code_paging, args.code_page_budget = prev


# ---------------------------------------------------------------------------
# pad-path units
# ---------------------------------------------------------------------------


def test_size_bucket_caps_at_page_budget(paging_defaults):
    arena = HostArena(4096)
    small = CodeTables(_program(100), arena)
    big = CodeTables(_program(3000), arena)
    assert page_budget() == 2048
    assert small.size_bucket()[0] == 512
    assert not small.is_paged()
    # the outlier's natural axis (8192) caps at the residency budget
    assert big.size_bucket()[0] == 2048
    assert big.full_instr_cap() == 8192
    assert big.is_paged()
    # escape hatch: --no-code-paging restores the unpaged growth
    args.code_paging = False
    assert big.size_bucket()[0] == 8192
    assert not big.is_paged()


def test_padded_tables_window_slices_instruction_axis(paging_defaults):
    arena = HostArena(4096)
    t = CodeTables(_program(5, n_pushes=3), arena)  # PUSH,PUSH,PUSH,POP*5
    cap = 4
    bucket = (cap, t.size_bucket()[1], _LOOPS_CAP)
    resident = t.padded_device_tables(bucket)
    assert list(resident[0]) == [O.F_PUSH] * 3 + [O.F_POP]
    windowed = t.padded_device_tables(bucket, window_base=3)
    assert list(windowed[0]) == [O.F_POP] * 4
    # window past the code end: real rows then the F_STOP pad fill
    tail = t.padded_device_tables(bucket, window_base=6)
    assert list(tail[0]) == [O.F_POP, O.F_POP, O.F_STOP, O.F_STOP]
    # jumpmap is NOT windowed: same byte-address axis either way
    assert np.array_equal(resident[6], windowed[6])


def test_stacked_tables_carry_pbase_column(paging_defaults):
    arena = HostArena(4096)
    tables = [CodeTables(_program(600), arena),
              CodeTables(_program(20), arena)]
    bucket = (8, 512, tables[0].size_bucket()[1], _LOOPS_CAP)
    cols = stacked_device_tables(tables, bucket, page_bases=[128, 0])
    assert len(cols) == 11  # 10 dispatch planes + the pbase column
    pbase = cols[-1]
    assert pbase.dtype == np.int32 and pbase.shape == (8,)
    assert list(pbase[:2]) == [128, 0] and not pbase[2:].any()
    # member 0's window starts at row 128; pad codes dispatch F_STOP
    assert cols[0][0][0] == tables[0].fam[128]
    assert (cols[0][3:] == O.F_STOP).all()


def test_pad_waste_pct_counts_unused_cells():
    arena = HostArena(4096)
    tables = [CodeTables(_program(15), arena),   # 16 rows with implicit STOP
              CodeTables(_program(99), arena)]   # 100 rows
    bucket = (8, 512, 32768, _LOOPS_CAP)
    expected = 100.0 * (1.0 - (16 + 100) / (8 * 512))
    assert pad_waste_pct(tables, bucket) == pytest.approx(expected)
    # a bucket the members fill exactly has no waste
    assert pad_waste_pct(tables, (2, 58, 32768, _LOOPS_CAP)) == pytest.approx(
        100.0 * (1.0 - (16 + 58) / (2 * 58))
    )


# ---------------------------------------------------------------------------
# outlier isolation
# ---------------------------------------------------------------------------


def test_bucket_classes_isolate_outlier(paging_defaults):
    arena = HostArena(8192)
    smalls = [CodeTables(_program(n), arena) for n in (20, 60, 200)]
    outlier = CodeTables(_program(3000), arena)
    tables = smalls + [outlier]

    single = multi_size_bucket(tables)
    classes = bucket_classes(tables)
    assert len(classes) == 2
    (small_bucket, small_members), (big_bucket, big_members) = classes
    # the small class keeps ITS axis — not the outlier's
    assert small_bucket[1] == 512 and small_members == [0, 1, 2]
    assert big_bucket[1] == 2048 and big_members == [3]
    # every member fits its class in every dimension
    for bucket, members in classes:
        assert len(members) <= bucket[0]
        for i in members:
            ic, ac, lc = tables[i].size_bucket()
            assert ic <= bucket[1] and ac <= bucket[2] and lc <= bucket[3]
    # the aggregate (cell-weighted) per-class waste beats the single bucket
    num = den = 0.0
    for bucket, members in classes:
        cells = bucket[0] * bucket[1]
        num += pad_waste_pct([tables[i] for i in members], bucket) * cells
        den += cells
    assert num / den < pad_waste_pct(tables, single)
    # coverage planes still span the WHOLE outlier (true-pc indexed)
    assert visited_instr_cap(tables) == 8192


def test_bucket_hint_classes_mirror_built_tables(paging_defaults):
    arena = HostArena(8192)
    lists = [_program(20), _program(60), _program(3000)]
    hints = bucket_hint_classes(lists)
    built = bucket_classes([CodeTables(pl, arena) for pl in lists])
    assert hints == [bucket for bucket, _members in built]


def test_pick_floor_rejects_partial_covers():
    floors = [(8, 512, 32768, 512), (1, 2048, 32768, 512)]
    # both cover; the smaller [C, instr] plane wins
    assert FrontierEngine._pick_floor(
        floors, (1, 512, 32768, 512)) == (1, 2048, 32768, 512)
    assert FrontierEngine._pick_floor(
        floors, (8, 512, 32768, 512)) == (8, 512, 32768, 512)
    # a floor covering only SOME dimensions would mint a third compiled
    # shape (elementwise max) — it must be skipped, not clamped
    assert FrontierEngine._pick_floor(
        floors, (16, 512, 32768, 512)) is None
    assert FrontierEngine._pick_floor([], (1, 512, 32768, 512)) is None


# ---------------------------------------------------------------------------
# page-fault park / repack
# ---------------------------------------------------------------------------


def _paged_engine(paging_defaults=None):
    """A bare engine with paging state for a 10-instruction code windowed
    to a 4-row axis (no laser, no device: the repack path is host-only)."""
    eng = object.__new__(FrontierEngine)
    arena = HostArena(1024)
    eng._page_tables = [CodeTables(_program(10), arena)]  # 11 rows
    eng._page_bucket = (1, 4, 32768, _LOOPS_CAP)
    eng._page_bases = [0]
    eng._page_pending = {}
    eng._page_fault_counts = {}
    eng._page_placer = lambda a: a
    return eng


def test_note_page_fault_schedules_window_over_pc(paging_defaults):
    eng = _paged_engine()
    assert eng._note_page_fault(0, 9) is True
    # a quarter-axis of context before the fault, clamped into the code
    assert eng._page_pending == {0: min(max(0, 9 - 1), 11 - 4)}
    # out-of-range code ids never repack
    assert eng._note_page_fault(7, 9) is False


def test_note_page_fault_storm_pins_host_side(paging_defaults):
    eng = _paged_engine()
    verdicts = [eng._note_page_fault(0, 5) for _ in range(10)]
    limit = FrontierEngine._PAGE_FAULT_LIMIT
    assert verdicts == [True] * limit + [False] * (10 - limit)


def test_maybe_repack_folds_pending_and_keeps_shapes(paging_defaults):
    eng = _paged_engine()
    assert eng._maybe_repack() is None  # nothing pending: no re-upload
    assert eng._note_page_fault(0, 9)
    code_dev = eng._maybe_repack()
    assert code_dev is not None
    assert eng._page_bases == [7] and eng._page_pending == {}
    assert int(code_dev.pbase[0]) == 7
    # same shapes as the resident stack: the compiled program is untouched
    base = stacked_device_tables(eng._page_tables, eng._page_bucket)
    for fresh, orig in zip(code_dev, base):
        assert np.asarray(fresh).shape == np.asarray(orig).shape
    # window content actually moved: row 0 now holds instruction 7
    assert code_dev.fam[0, 0] == eng._page_tables[0].fam[7]
    assert eng._maybe_repack() is None  # pending drained


def test_device_dispatch_faults_off_window_pc(paging_defaults):
    jax = pytest.importorskip("jax")
    from mythril_tpu.frontier.step import (
        ArenaDev, CfgScalars, CodeDev, cached_segment,
    )

    caps = Caps(B=2, K=1)
    arena = HostArena(caps.ARENA)
    row_zero = arena.const_row(0, 256)
    row_one = arena.const_row(1, 256)
    tables = CodeTables(_program(10), arena)  # POP*10 + implicit STOP
    instr_cap = 4  # window: rows 0..3 resident
    _ic, addr_cap, loops_cap = tables.size_bucket()
    bucket = (1, instr_cap, addr_cap, loops_cap)
    segment = cached_segment(caps, 1, instr_cap, addr_cap, loops_cap)
    code_dev = CodeDev(*[
        jax.device_put(a) for a in stacked_device_tables([tables], bucket)
    ])
    cfg = CfgScalars(
        max_depth=np.int32(128), loop_bound=np.int32(0),
        row_zero=np.int32(row_zero), row_one=np.int32(row_one),
        sel_mode=np.int32(0),
    )
    st = empty_state(caps, loops_cap)
    for slot, pc in enumerate((2, 6)):  # resident / off-window
        st.seed[slot] = 0
        st.halt[slot] = O.H_RUNNING
        st.pc[slot] = pc
        st.stack[slot, 0] = row_one
        st.stack_len[slot] = 1
    dev_arena = ArenaDev(*[jax.device_put(a) for a in arena.device_arrays()])
    visited = jax.device_put(np.zeros((3, 1, 16), bool))
    out, _arena, _alen, _n, _ml, _visited = segment(
        st, dev_arena, arena.length, visited, code_dev, cfg
    )
    halts = np.array(out.halt)
    assert halts[0] == O.H_RUNNING  # resident pc executed its POP
    assert int(np.array(out.pc)[0]) == 3
    assert halts[1] == O.H_PAGE_FAULT  # off-window pc faulted, untouched
    assert int(np.array(out.pc)[1]) == 6
    assert int(np.array(out.stack_len)[1]) == 1  # arity forced to 0: no pops
    assert int(np.array(out.ev_len)[1]) == 0  # faults never emit events


# ---------------------------------------------------------------------------
# paged-vs-resident parity (end to end)
# ---------------------------------------------------------------------------


def _pad_tail_kill(n_pad: int) -> bytes:
    """Selector dispatch to CALLER;SELFDESTRUCT placed BEYOND a straight-
    line pad tail — the deep cold-jump shape (bench.py largecode_mixed)."""
    sel = 0x41C0E1B5  # kill()
    tail = bytes([0x60, 0x00, 0x50]) * n_pad + bytes([0x00])
    dest = 16 + len(tail)
    head = bytes([
        0x60, 0x00, 0x35, 0x60, 0xE0, 0x1C,
        0x63, (sel >> 24) & 0xFF, (sel >> 16) & 0xFF,
        (sel >> 8) & 0xFF, sel & 0xFF,
        0x14, 0x61, (dest >> 8) & 0xFF, dest & 0xFF, 0x57,
    ])
    return head + tail + bytes([0x5B, 0x33, 0xFF])


@pytest.mark.slow
def test_paged_vs_resident_issue_parity():
    """The whole optimization, end to end: a code big enough to page (at a
    shrunken budget) analyzed with paging ON finds the EXACT issue set of
    the fully-resident run — and finds the deep SELFDESTRUCT exactly once
    (the faulted path re-injects once after the repack; it is not lost and
    not duplicated)."""
    from mythril_tpu.analysis.module.loader import ModuleLoader
    from mythril_tpu.analysis.security import (
        fire_lasers,
        reset_callback_modules,
    )
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.observability.metrics import get_registry

    code = _pad_tail_kill(400)  # ~816 instrs: pages at a 512-row budget

    def analyze():
        # same code + address twice in-process: drop the per-(address,
        # bytecode) detector caches or the second run reports nothing
        reset_callback_modules()
        for module in ModuleLoader().get_detection_modules():
            module.cache.clear()
        sym = SymExecWrapper(
            code, address=0x0901D12E, strategy="bfs",
            transaction_count=1, execution_timeout=120,
            modules=["AccidentallyKillable"],
        )
        issues = fire_lasers(sym, white_list=["AccidentallyKillable"])
        return sorted((i.swc_id, i.address) for i in issues)

    prev = (args.frontier, args.frontier_force, args.code_paging,
            args.code_page_budget, args.probe_backend)
    reg = get_registry()
    try:
        args.probe_backend = "auto"
        args.frontier = True
        args.frontier_force = True
        args.code_paging, args.code_page_budget = True, 512
        faults_before = reg.counter("frontier.page_faults").value
        paged = analyze()
        faults = reg.counter("frontier.page_faults").value - faults_before
        args.code_paging = False
        resident = analyze()
    finally:
        (args.frontier, args.frontier_force, args.code_paging,
         args.code_page_budget, args.probe_backend) = prev
    assert paged == resident, "paging changed the issue set"
    assert [s for s, _ in paged].count("106") == 1, (
        "deep SELFDESTRUCT must surface exactly once"
    )
    assert faults > 0, "the cold-jump target never faulted the window"
