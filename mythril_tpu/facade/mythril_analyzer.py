"""Analyzer facade: run symbolic execution + detectors per contract.

Reference parity: mythril/mythril/mythril_analyzer.py:27-189 — copies CLI
args into the global flag object, runs fire_lasers per contract with graceful
degradation to partial results, and offers statespace/graph dumps.
"""

from __future__ import annotations

import logging
import traceback
from dataclasses import dataclass, field
from typing import List, Optional

from mythril_tpu.analysis.report import Issue, Report
from mythril_tpu.analysis.security import fire_lasers, retrieve_callback_issues
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.smt.solver import SolverStatistics
from mythril_tpu.support.support_args import args

log = logging.getLogger(__name__)


@dataclass
class AnalyzerArgs:
    strategy: str = "dfs"
    max_depth: int = 128
    execution_timeout: int = 86400
    create_timeout: int = 10
    loop_bound: int = 3
    call_depth_limit: int = 3
    transaction_count: int = 2
    modules: Optional[List[str]] = None
    disable_dependency_pruning: bool = False
    solver_timeout: int = 10000
    unconstrained_storage: bool = False
    sparse_pruning: bool = False
    parallel_solving: bool = False
    solver_log: Optional[str] = None
    enable_iprof: bool = False
    benchmark_path: Optional[str] = None
    enable_coverage_strategy: bool = False
    custom_modules_directory: str = ""
    checkpoint_file: Optional[str] = None
    resume_from: Optional[str] = None
    probe_backend: str = "auto"
    frontier: bool = False
    frontier_width: int = 64
    frontier_force: bool = False
    query_cache: bool = True
    query_cache_dir: Optional[str] = None
    staticpass: bool = True
    staticpass_interproc: bool = True
    code_paging: bool = True
    code_page_budget: int = 2048
    pipeline: bool = True
    prefilter: bool = True
    devsolver: bool = True
    devsolver_bit_budget: int = 64
    devsolver_iters: int = 2048
    frontier_mesh: bool = True
    adaptive: bool = True
    coverage_target: Optional[float] = None
    solver_workers: int = 2
    harvest_workers: int = 4
    compile_cache_dir: Optional[str] = None
    # one directory pinning BOTH persistent caches (querycache/ + xla/);
    # explicit query_cache_dir / compile_cache_dir win over the derivation
    cache_root: Optional[str] = None
    heartbeat_out: Optional[str] = None
    heartbeat_interval: float = 0.5
    flight_recorder: Optional[str] = None
    watchdog_deadline: Optional[float] = None
    #: record the metrics registry into a persistent delta-encoded
    #: history ring under this directory (``myth history`` reads it)
    history_dir: Optional[str] = None


class MythrilAnalyzer:
    def __init__(
        self,
        disassembler,
        cmd_args: AnalyzerArgs,
        strategy: str = "dfs",
        address: Optional[str] = None,
    ):
        self.eth = disassembler.eth
        self.contracts = disassembler.contracts or []
        self.enable_online_lookup = disassembler.enable_online_lookup
        self.strategy = strategy or cmd_args.strategy
        self.address = address
        self.cmd_args = cmd_args

        # anchor issue discovery timestamps before any analysis starts
        from mythril_tpu.analysis.report import StartTime

        StartTime()

        # propagate flags to the global args object (reference :63-70);
        # shared with the long-lived service daemon (facade/warm.py) so
        # one-shot and warm-process runs configure the engine identically
        from mythril_tpu.facade.warm import apply_analyzer_args

        apply_analyzer_args(cmd_args)

    def _sym_exec(self, contract, run_analysis_modules: bool = True) -> SymExecWrapper:
        from mythril_tpu.support.loader import DynLoader

        dynloader = DynLoader(self.eth, active=self.eth is not None)
        return SymExecWrapper(
            contract,
            self.address or "0x" + "0" * 38 + "06",
            strategy=self.strategy,
            dynloader=dynloader,
            max_depth=self.cmd_args.max_depth,
            execution_timeout=self.cmd_args.execution_timeout,
            create_timeout=self.cmd_args.create_timeout,
            loop_bound=self.cmd_args.loop_bound,
            transaction_count=self.cmd_args.transaction_count,
            modules=self.cmd_args.modules,
            disable_dependency_pruning=self.cmd_args.disable_dependency_pruning,
            run_analysis_modules=run_analysis_modules,
            enable_coverage_strategy=self.cmd_args.enable_coverage_strategy,
            custom_modules_directory=self.cmd_args.custom_modules_directory,
        )

    def dump_statespace(self, contract=None) -> str:
        import json

        from mythril_tpu.analysis.traceexplore import get_serializable_statespace

        sym = self._sym_exec(
            contract or self.contracts[0], run_analysis_modules=False
        )
        return json.dumps(get_serializable_statespace(sym))

    def graph_html(
        self, contract=None, enable_physics: bool = False, phrackify: bool = False
    ) -> str:
        from mythril_tpu.analysis.callgraph import generate_graph

        sym = self._sym_exec(
            contract or self.contracts[0], run_analysis_modules=False
        )
        return generate_graph(sym, physics=enable_physics, phrackify=phrackify)

    def fire_lasers(self, modules: Optional[List[str]] = None) -> Report:
        from mythril_tpu.frontier.engine import reset_isolation_gauges

        reset_isolation_gauges()
        SolverStatistics().enabled = True
        benchmark_base = args.benchmark_path
        try:
            all_issues, exceptions, execution_info = self._fire_lasers_loop(
                modules, benchmark_base
            )
        finally:
            args.benchmark_path = benchmark_base

        source_data = self.contracts
        report = Report(
            contracts=source_data,
            exceptions=exceptions,
            execution_info=execution_info,
        )
        for issue in all_issues:
            report.append_issue(issue)
        return report

    def _fire_lasers_loop(self, modules, benchmark_base):
        all_issues: List[Issue] = []
        exceptions = []
        execution_info = []
        for n_contract, contract in enumerate(self.contracts):
            if benchmark_base and len(self.contracts) > 1:
                # one series file per contract instead of silent overwrites
                args.benchmark_path = f"{benchmark_base}.{n_contract}"
            # the telemetry singletons are process-wide: without a
            # per-contract sweep, contract N's jsonv2 meta would report
            # parks/segment time/solver queries accumulated from earlier
            # contracts in the same invocation.  The sweep clears every
            # non-persistent metric (FrontierStatistics and
            # SolverStatistics facades included); the frontier's per-code
            # slow/narrow verdicts are persistent-scope and survive — see
            # reset_analysis_metrics / frontier/engine.py.
            from mythril_tpu.observability import reset_analysis_metrics

            reset_analysis_metrics()
            try:
                sym = self._sym_exec(contract)
                issues = fire_lasers(sym, modules or self.cmd_args.modules)
                from mythril_tpu.core.execution_info import (
                    CalibrationInfo,
                    EngineStatsInfo,
                    FrontierStatsInfo,
                    SolverStatsInfo,
                )

                execution_info = [
                    EngineStatsInfo(sym.laser),
                    SolverStatsInfo(),
                    CalibrationInfo(),
                ]
                if args.frontier:
                    execution_info.append(FrontierStatsInfo())
            except KeyboardInterrupt:
                log.critical("keyboard interrupt: saving partial results")
                issues = retrieve_callback_issues(modules or self.cmd_args.modules)
            except Exception:  # noqa: BLE001 - graceful degradation to partial results
                log.exception("exception during analysis; saving partial results")
                issues = retrieve_callback_issues(modules or self.cmd_args.modules)
                exceptions.append(traceback.format_exc())
            from mythril_tpu.support.signatures import SignatureDB

            sigdb = SignatureDB()
            for issue in issues:
                issue.add_code_info(contract)
                issue.resolve_function_name(sigdb)
            log.info("solver statistics: %s", SolverStatistics())
            all_issues += issues
        return all_issues, exceptions, execution_info
