"""Interactive HTML call-graph export (vis.js-style single-file report).

Reference parity: mythril/analysis/callgraph.py + templates/callgraph.html —
rendered with an inline template (no external assets; the vis.js payload is
loaded from a CDN tag so the file remains standalone-readable offline as a
plain node/edge listing).
"""

from __future__ import annotations

import json
import re

_TEMPLATE = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>Call Graph</title>
<script src="https://cdnjs.cloudflare.com/ajax/libs/vis/4.21.0/vis.min.js"></script>
<link href="https://cdnjs.cloudflare.com/ajax/libs/vis/4.21.0/vis.min.css" rel="stylesheet" type="text/css">
<style type="text/css">
  body, html { margin: 0; height: 100%; background: #1a1a1a; color: #e0e0e0; }
  #mynetwork { width: 100%; height: 100%; }
</style>
</head>
<body>
<div id="mynetwork"></div>
<script>
  var nodes = new vis.DataSet(__NODES__);
  var edges = new vis.DataSet(__EDGES__);
  var container = document.getElementById("mynetwork");
  var data = { nodes: nodes, edges: edges };
  var options = {
    physics: { enabled: __PHYSICS__ },
    layout: { improvedLayout: true },
    nodes: { shape: "box", font: { face: "monospace", color: "#e0e0e0", size: 11 },
             color: { background: "#26262d", border: "#9e42b3" } },
    edges: { font: { color: "#aaaaaa", size: 9 }, arrows: "to", color: "#555" }
  };
  var network = new vis.Network(container, data, options);
</script>
</body>
</html>
"""


def _node_label(node, max_lines: int = 25) -> str:
    lines = [f"{node.function_name} (uid {node.uid})"]
    for state in node.states[:max_lines]:
        instr = state.get_current_instruction()
        arg = f" {instr.get('argument', '')}" if instr.get("argument") else ""
        lines.append(f"{instr['address']} {instr['opcode']}{arg}")
    if len(node.states) > max_lines:
        lines.append("...")
    return "\n".join(lines)


def generate_graph(statespace, physics: bool = False, phrackify: bool = False) -> str:
    """Render the statespace's nodes/edges into the HTML template."""
    nodes = [
        {"id": str(node.uid), "label": _node_label(node), "size": 150}
        for node in statespace.nodes.values()
    ]
    edges = []
    for edge in statespace.edges:
        label = ""
        if edge.condition is not None:
            label = re.sub(r"\s+", " ", repr(edge.condition))[:100]
        edges.append(
            {
                "from": str(edge.node_from),
                "to": str(edge.node_to),
                "label": label,
                "arrows": "to",
            }
        )
    html = _TEMPLATE.replace("__NODES__", json.dumps(nodes))
    html = html.replace("__EDGES__", json.dumps(edges))
    html = html.replace("__PHYSICS__", "true" if physics else "false")
    return html
