// Batched keccak-256 on the host path.
//
// Counterpart of the reference's pysha3 C extension (SURVEY.md §2.9): concrete
// hashing for code hashes, selectors, and the probe's model validation.  The
// device path has its own Pallas kernel (mythril_tpu/ops/keccak_pallas.py);
// this one serves host Python via ctypes (mythril_tpu/native/keccak.py).

#include <cstdint>
#include <cstring>

namespace {

const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

const int ROT[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55, 20, 3,  10, 43,
                     25, 39, 41, 45, 15, 21, 8,  18, 2,  61, 56, 14};

inline uint64_t rotl(uint64_t x, int n) {
  return n == 0 ? x : (x << n) | (x >> (64 - n));
}

void keccak_f1600(uint64_t st[25]) {
  for (int round = 0; round < 24; round++) {
    uint64_t bc[5], t;
    // theta
    for (int x = 0; x < 5; x++)
      bc[x] = st[x] ^ st[x + 5] ^ st[x + 10] ^ st[x + 15] ^ st[x + 20];
    for (int x = 0; x < 5; x++) {
      t = bc[(x + 4) % 5] ^ rotl(bc[(x + 1) % 5], 1);
      for (int y = 0; y < 25; y += 5) st[x + y] ^= t;
    }
    // rho + pi
    uint64_t b[25];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++) {
        int src = x + 5 * y;
        int dst = y + 5 * ((2 * x + 3 * y) % 5);
        b[dst] = rotl(st[src], ROT[src]);
      }
    // chi
    for (int y = 0; y < 25; y += 5)
      for (int x = 0; x < 5; x++)
        st[y + x] = b[y + x] ^ ((~b[y + (x + 1) % 5]) & b[y + (x + 2) % 5]);
    // iota
    st[0] ^= RC[round];
  }
}

void keccak256_one(const uint8_t* data, int64_t len, uint8_t* out) {
  const int64_t RATE = 136;
  uint64_t st[25];
  std::memset(st, 0, sizeof(st));
  int64_t off = 0;
  while (len - off >= RATE) {
    for (int i = 0; i < RATE / 8; i++) {
      uint64_t lane;
      std::memcpy(&lane, data + off + 8 * i, 8);
      st[i] ^= lane;  // little-endian host assumed (x86/ARM)
    }
    keccak_f1600(st);
    off += RATE;
  }
  uint8_t block[136];
  std::memset(block, 0, sizeof(block));
  std::memcpy(block, data + off, (size_t)(len - off));
  block[len - off] = 0x01;  // keccak (pre-NIST) padding
  block[RATE - 1] |= 0x80;
  for (int i = 0; i < RATE / 8; i++) {
    uint64_t lane;
    std::memcpy(&lane, block + 8 * i, 8);
    st[i] ^= lane;
  }
  keccak_f1600(st);
  std::memcpy(out, st, 32);
}

}  // namespace

extern "C" {

// n messages of uniform byte length `len` (concatenated) -> n x 32-byte digests
void keccak256_batch(const uint8_t* data, int64_t n, int64_t len, uint8_t* out) {
  for (int64_t i = 0; i < n; i++)
    keccak256_one(data + i * len, len, out + i * 32);
}

void keccak256_single(const uint8_t* data, int64_t len, uint8_t* out) {
  keccak256_one(data, len, out);
}

}  // extern "C"
