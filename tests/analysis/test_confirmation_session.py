"""Confirmation pipelining: one CDCL blast per transaction-end sweep.

Round-4 gap (VERDICT r4 "Missing #1"): the tx-end gate proved feasibility in
one shared session, then every confirmed issue's get_transaction_sequence
re-blasted the whole path condition.  The reference pays exactly one
z3.Optimize per issue (mythril/analysis/solver.py:51-101); the shared-session
pipeline pays one blast per SWEEP — confirmations answer their initial solve
and every minimization bound query under assumptions on the gate's live
session.
"""

import pytest

from mythril_tpu.native import bitblast
from tests.analysis.test_detectors import analyze

# one path, two independent ADD-overflow -> SSTORE sinks:
#   storage[0] = calldataload(0) + calldataload(0x20)
#   storage[1] = calldataload(0x40) + calldataload(0x60)
# both park PotentialIssues before the single STOP, so ONE tx-end sweep
# sees two pending issues and must confirm both
TWO_OVERFLOWS = "600035602035016000556040356060350160015500"


@pytest.mark.skipif(not bitblast.available(), reason="native solver unavailable")
def test_one_blast_per_tx_end_sweep(monkeypatch):
    real = bitblast.OptimizeSession
    built = []

    class Counting(real):
        def __init__(self, *a, **k):
            built.append(1)
            super().__init__(*a, **k)

    monkeypatch.setattr(bitblast, "OptimizeSession", Counting)
    issues = analyze(TWO_OVERFLOWS, modules=["IntegerArithmetics"])
    overflow_issues = [i for i in issues if i.swc_id == "101"]
    assert len(overflow_issues) == 2, "both overflow sinks must confirm"
    for issue in overflow_issues:
        steps = issue.transaction_sequence["steps"]
        assert steps and steps[-1]["input"].startswith("0x")
    # the gate blasts path+sanity+objectives once; both confirmations run
    # under assumptions on that session instead of re-blasting
    assert sum(built) == 1, f"expected 1 session blast, saw {sum(built)}"
