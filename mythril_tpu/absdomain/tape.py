"""Pack a batch of constraint rows onto one shared abstract-evaluation tape.

The pre-filter reuses ``native/bitblast.py``'s serialization wholesale: the
UNION of every row's conjuncts is serialized once (interned terms make
sibling rows share their entire path prefix, so the union tape is barely
larger than the widest single row), and each row keeps only the list of tape
nodes it actually asserts.  Evaluation then runs one pass over the tape with
a row axis — the whole frontier batch at once.

Every abstraction the serializer applies (mux-chain ``select`` rewrite,
fresh variables for base-array selects / keccak / apply, dropped select
congruence under ``lazy_selects=True``) only ever ADDS behaviors, so
bottom-by-abstraction at any asserted root proves the ORIGINAL row UNSAT.

Alongside the tape this module harvests per-row *narrowing overrides* —
exact integer range pins read off the row's own conjuncts (``x == c``,
``cnt <= 1``), mirroring ``smt/intervals.py``'s harvest — and converts them
to the dual-domain representation (directed-rounded float64 bounds plus
common-prefix known bits).  Overrides are met into the evaluation at the
overridden node for that row only.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from mythril_tpu.native import bitblast
from mythril_tpu.native.bitblast import OP_CONST, Unsupported
from mythril_tpu.smt import terms
from mythril_tpu.smt.terms import Term

# 32-bit limbs; 16 limbs cover every width the engine emits (mul/overflow
# demands build 512-bit intermediates).  Wider tapes fall through.
LIMBS = 16
MAX_WIDTH = 32 * LIMBS
U32 = np.uint32
_ALL = U32(0xFFFFFFFF)

# Conservative node budget for one packed batch: far below the blaster's
# 200k cap — the pre-filter must stay a near-free pass, and anything this
# large is better spent in the exact tiers.
MAX_NODES = 4096


def _f_under(v: int) -> float:
    """Largest float64 <= v (directed rounding for interval lower bounds)."""
    f = float(v)
    return f if int(f) <= v else float(np.nextafter(f, -np.inf))


def _f_over(v: int) -> float:
    """Smallest float64 >= v."""
    f = float(v)
    return f if int(f) >= v else float(np.nextafter(f, np.inf))


def _limbs_of(v: int) -> np.ndarray:
    out = np.zeros(LIMBS, U32)
    for i in range(LIMBS):
        out[i] = (v >> (32 * i)) & 0xFFFFFFFF
    return out


def width_mask(w: int) -> np.ndarray:
    """Per-limb mask of the bits below ``w``."""
    out = np.zeros(LIMBS, U32)
    for i in range(LIMBS):
        base = 32 * i
        if w >= base + 32:
            out[i] = _ALL
        elif w > base:
            out[i] = U32((1 << (w - base)) - 1)
    return out


class _RowRefuted(Exception):
    """Harvested narrowings for one row are mutually exclusive."""


class PackedBatch:
    """One serialized union tape plus per-row assertion/override data."""

    def __init__(self, n_rows: int):
        self.n_rows = n_rows
        self.n_nodes = 0
        # tape node arrays, all [N]-indexed
        self.op = np.zeros(0, np.int32)
        self.w = np.zeros(0, np.int32)
        self.a0 = np.zeros(0, np.int32)
        self.a1 = np.zeros(0, np.int32)
        self.a2 = np.zeros(0, np.int32)
        self.x0 = np.zeros(0, np.int32)
        self.x1 = np.zeros(0, np.int32)
        self.wm = np.zeros((0, LIMBS), U32)     # width masks
        self.c_limbs = np.zeros((0, LIMBS), U32)  # OP_CONST payloads
        self.c_lo = np.zeros(0, np.float64)
        self.c_hi = np.zeros(0, np.float64)
        # per-row asserted root nodes
        self.row_roots: List[List[int]] = [[] for _ in range(n_rows)]
        # rows refuted already at harvest time (contradictory narrowings)
        self.row_refuted = np.zeros(n_rows, bool)
        # node -> (olo[R], ohi[R], okm[R,L], okv[R,L]) narrowing overrides
        self.overrides: Dict[int, Tuple[np.ndarray, np.ndarray,
                                        np.ndarray, np.ndarray]] = {}
        # node -> [(row, lo, hi)] exact integer bounds for the same
        # narrowings: float64 cannot represent values like 2^256-1, so the
        # verdict pass re-checks each harvested demand against the exact
        # known-bits element with python-int arithmetic
        self.ov_exact: Dict[int, List[Tuple[int, int, int]]] = {}


def _override_slot(pack: PackedBatch, node: int):
    ov = pack.overrides.get(node)
    if ov is None:
        r = pack.n_rows
        ov = (
            np.zeros(r, np.float64),
            np.full(r, np.inf, np.float64),
            np.zeros((r, LIMBS), U32),
            np.zeros((r, LIMBS), U32),
        )
        pack.overrides[node] = ov
    return ov


def _apply_narrowing(pack: PackedBatch, row: int, node: int, w: int,
                     ranges: Dict[int, Tuple[int, int]]) -> None:
    """Install one row's final integer range for ``node`` into the pack."""
    lo, hi = ranges[node]
    olo, ohi, okm, okv = _override_slot(pack, node)
    olo[row] = _f_under(lo)
    ohi[row] = _f_over(hi)
    # every value in [lo, hi] shares the bits above the highest differing
    # bit of the bounds: those bits are KNOWN for this row
    k = (lo ^ hi).bit_length()
    known = ((1 << w) - 1) & ~((1 << k) - 1)
    okm[row] = _limbs_of(known)
    okv[row] = _limbs_of(lo & known)
    pack.ov_exact.setdefault(node, []).append((row, lo, hi))


def _harvest_row(conjuncts: Sequence[Term],
                 narrow) -> None:
    """``smt/intervals.py``-style range harvest over one row's conjuncts."""
    for c in conjuncts:
        _harvest(c, True, narrow)


def _signed(v: int, w: int) -> int:
    return v - (1 << w) if v >= (1 << (w - 1)) else v


def _harvest(t: Term, want: bool, narrow) -> None:
    op = t.op
    if op == "const" and t.sort is terms.BOOL:
        if bool(t.aux) != want:
            raise _RowRefuted
        return
    if op == "and" and want:
        for a in t.args:
            _harvest(a, True, narrow)
        return
    if op == "or" and not want:
        # De Morgan: Not(a | b | ...) == Not(a) & Not(b) & ...
        for a in t.args:
            _harvest(a, False, narrow)
        return
    if op == "not":
        _harvest(t.args[0], not want, narrow)
        return
    if op == "xor":
        # boolean xor against a constant is (possibly negated) assertion
        # of the other side: x ^ true == Not(x)
        a, b = t.args
        if a.sort is terms.BOOL:
            if a.op == "const":
                _harvest(b, want != bool(a.aux), narrow)
            elif b.op == "const":
                _harvest(a, want != bool(b.aux), narrow)
        return
    if op == "eq":
        a, b = t.args
        if a.sort is terms.BOOL:
            # boolean equality against a constant asserts the other side
            # (negated for eq(x, false) / Not(eq(x, true)))
            if a.op == "const":
                _harvest(b, want == bool(a.aux), narrow)
            elif b.op == "const":
                _harvest(a, want == bool(b.aux), narrow)
            return
        if not terms.is_bv_sort(a.sort):
            return
        if want:
            if a.is_const:
                narrow(b, a.value, a.value)
            elif b.is_const:
                narrow(a, b.value, b.value)
        return
    if op in ("ult", "ule"):
        a, b = t.args
        strict = op == "ult"
        if want:
            if a.is_const and not b.is_const:
                narrow(b, a.value + (1 if strict else 0), (1 << b.width) - 1)
            elif b.is_const and not a.is_const:
                narrow(a, 0, b.value - (1 if strict else 0))
        else:
            # Not(a < b) == b <= a; Not(a <= b) == b < a
            if b.is_const and not a.is_const:
                narrow(a, b.value + (0 if strict else 1), (1 << a.width) - 1)
            elif a.is_const and not b.is_const:
                narrow(b, 0, a.value - (0 if strict else 1))
        return
    if op in ("slt", "sle"):
        # signed comparisons pin one side only when the satisfying set is
        # a single unsigned interval (the two's-complement wraparound
        # splits the other polarity into a union the domain cannot hold)
        a, b = t.args
        strict = op == "slt"
        if not want:
            # Not(a <s b) == b <=s a ; Not(a <=s b) == b <s a
            a, b = b, a
            strict = not strict
        if not terms.is_bv_sort(a.sort):
            return
        w = a.width
        half, full = 1 << (w - 1), 1 << w
        if b.is_const and not a.is_const:
            # signed(a) < upper (strict normal form)
            upper = _signed(b.value, w) + (0 if strict else 1)
            if upper <= 0:
                # wholly inside the negative half: [half, upper-1 mod 2^w]
                narrow(a, half, (upper - 1) % full)
        elif a.is_const and not b.is_const:
            # signed(b) >= lower
            lower = _signed(a.value, w) + (1 if strict else 0)
            if lower >= 0:
                # wholly inside the non-negative half
                narrow(b, lower, half - 1)
        return


def pack(rows: Sequence[Sequence[Term]],
         max_nodes: int = MAX_NODES) -> PackedBatch:
    """Serialize the union of ``rows`` and build per-row assertion data.

    Raises ``bitblast.Unsupported`` when the union carries structure the
    abstract tape cannot express (array equality, >512-bit nodes, node
    budget blown) — callers treat that as fallthrough, never as a verdict.
    """
    union: List[Term] = []
    seen: set = set()
    for row in rows:
        for c in row:
            if c.tid not in seen:
                seen.add(c.tid)
                union.append(c)

    tape = bitblast.serialize(union, lazy_selects=True)
    n = len(tape.records)
    if n > max_nodes:
        raise Unsupported("prefilter tape too large (%d nodes)" % n)

    p = PackedBatch(len(rows))
    p.n_nodes = n
    p.node_of = dict(tape.node_of)  # tid -> node (differential tests)
    rec = np.asarray(tape.records, np.int64).reshape(n, 7)
    p.op = rec[:, 0].astype(np.int32)
    p.w = rec[:, 1].astype(np.int32)
    p.a0 = rec[:, 2].astype(np.int32)
    p.a1 = rec[:, 3].astype(np.int32)
    p.a2 = rec[:, 4].astype(np.int32)
    p.x0 = rec[:, 5].astype(np.int32)
    p.x1 = rec[:, 6].astype(np.int32)
    if int(p.w.max(initial=0)) > MAX_WIDTH:
        raise Unsupported("node wider than %d bits" % MAX_WIDTH)

    p.wm = np.zeros((n, LIMBS), U32)
    p.c_limbs = np.zeros((n, LIMBS), U32)
    p.c_lo = np.zeros(n, np.float64)
    p.c_hi = np.zeros(n, np.float64)
    consts = bytes(tape.consts)
    for i in range(n):
        w = int(p.w[i])
        p.wm[i] = width_mask(w)
        if p.op[i] == OP_CONST:
            off, nb = int(p.x0[i]), int(p.x1[i])
            v = int.from_bytes(consts[off:off + nb], "little") & ((1 << w) - 1)
            p.c_limbs[i] = _limbs_of(v)
            p.c_lo[i] = _f_under(v)
            p.c_hi[i] = _f_over(v)

    for r, row in enumerate(rows):
        p.row_roots[r] = [tape.node_of[c.tid] for c in row]
        ranges: Dict[int, Tuple[int, int]] = {}
        widths: Dict[int, int] = {}

        def narrow(t: Term, lo: int, hi: int) -> None:
            node = tape.node_of.get(t.tid)
            if node is None:
                return
            w = t.width if terms.is_bv_sort(t.sort) else 1
            lo, hi = max(lo, 0), min(hi, (1 << w) - 1)
            cur = ranges.get(node)
            if cur is not None:
                lo, hi = max(lo, cur[0]), min(hi, cur[1])
            if lo > hi:
                raise _RowRefuted
            ranges[node] = (lo, hi)
            widths[node] = w

        try:
            _harvest_row(row, narrow)
        except _RowRefuted:
            p.row_refuted[r] = True
            continue
        for node in ranges:
            _apply_narrowing(p, r, node, widths[node], ranges)
    return p
