"""Differential test: the tape VM must agree bit-exactly with concrete_eval.

Same contract as tests/ops/test_lowering.py, but for the single-compile
interpreter path — the production device probe.  Every case also re-runs
through a second compile_tape call to confirm the cache returns a working
object, and mixed-profile coverage ensures the padding/resolve logic is
exercised for both profile sizes.
"""

import random

import pytest

from mythril_tpu.ops import tape_vm
from mythril_tpu.smt import terms
from mythril_tpu.smt.concrete_eval import ArrayValue, Assignment, evaluate


def _random_assignments(bv_vars, array_vars, rng, n):
    out = []
    for _ in range(n):
        asg = Assignment()
        for v in bv_vars:
            if v.sort is terms.BOOL:
                asg.scalars[v] = rng.random() < 0.5
                continue
            choice = rng.random()
            if choice < 0.25:
                asg.scalars[v] = rng.randint(0, 5)
            elif choice < 0.5:
                asg.scalars[v] = terms.mask(-rng.randint(1, 5), v.width)
            else:
                asg.scalars[v] = rng.getrandbits(v.width)
        for av in array_vars:
            backing = {
                rng.getrandbits(av.sort[1]) % 64: rng.getrandbits(av.sort[2])
                for _ in range(rng.randint(0, 4))
            }
            asg.arrays[av] = ArrayValue(backing, default=rng.getrandbits(8))
        out.append(asg)
    return out


def _check(conjuncts, assignments):
    compiled = tape_vm.compile_tape(conjuncts)
    got = compiled.evaluate_batch(assignments)
    for b, asg in enumerate(assignments):
        vals = evaluate(conjuncts, asg)
        want = [bool(vals[c]) for c in conjuncts]
        assert list(got[b]) == want, f"candidate {b}: {list(got[b])} != {want}"


def test_arithmetic_and_compare_ops():
    rng = random.Random(11)
    x = terms.var("tx", 256)
    y = terms.var("ty", 256)
    z = terms.var("tz", 64)
    conjuncts = [
        terms.eq(terms.add(x, y), terms.const(100, 256)),
        terms.ult(terms.mul(x, terms.const(3, 256)), y),
        terms.eq(terms.udiv(x, y), terms.const(2, 256)),
        terms.eq(terms.sdiv(x, y), terms.const(2, 256)),
        terms.eq(terms.urem(x, terms.const(7, 256)), terms.const(3, 256)),
        terms.eq(terms.srem(x, y), terms.sub(x, y)),
        terms.sle(terms.neg(z), z),
        terms.slt(z, terms.const(12, 64)),
        terms.ule(y, terms.bvexp(terms.const(2, 256), x)),
        terms.eq(terms.band(x, y), terms.bor(x, terms.bnot(y))),
    ]
    _check(conjuncts, _random_assignments([x, y, z], [], rng, 37))


def test_shift_concat_extract_sext():
    rng = random.Random(13)
    x = terms.var("tsx", 256)
    s = terms.var("tss", 256)
    n = terms.var("tsn", 32)
    conjuncts = [
        terms.eq(terms.shl(x, s), terms.const(0x80, 256)),
        terms.eq(terms.lshr(x, terms.const(4, 256)), terms.const(1, 256)),
        terms.ult(terms.ashr(x, s), x),
        terms.eq(
            terms.concat2(terms.extract(31, 0, x), n),
            terms.const(0xDEADBEEF_12345678, 64),
        ),
        terms.eq(terms.sext(n, 32), terms.zext(n, 32)),
        terms.ult(terms.sext(terms.extract(7, 0, x), 248), x),
    ]
    _check(conjuncts, _random_assignments([x, s, n], [], rng, 29))


def test_bool_ops_and_ite():
    rng = random.Random(17)
    p = terms.bool_var("tbp")
    q = terms.bool_var("tbq")
    x = terms.var("tbx", 8)
    conjuncts = [
        terms.lor(p, q),
        terms.lnot(terms.land(p, q)),
        terms.eq(terms.ite(p, x, terms.const(7, 8)), terms.const(7, 8)),
        terms.lxor(p, terms.ult(x, terms.const(100, 8))),
    ]
    _check(conjuncts, _random_assignments([p, q, x], [], rng, 23))


def test_array_select_store_chains():
    rng = random.Random(19)
    a = terms.array_var("tva", 256, 256)
    i = terms.var("tvi", 256)
    stored = terms.store(
        terms.store(a, terms.const(5, 256), terms.const(42, 256)),
        i,
        terms.const(9, 256),
    )
    conjuncts = [
        terms.eq(terms.select(stored, terms.const(5, 256)), terms.const(42, 256)),
        terms.eq(terms.select(stored, i), terms.const(9, 256)),
        terms.ult(terms.select(a, terms.const(0, 256)), terms.const(50, 256)),
        terms.eq(terms.select(a, i), terms.select(stored, terms.const(7, 256))),
    ]
    _check(conjuncts, _random_assignments([i], [a], rng, 31))


def test_keccak_32_and_64_byte_preimages():
    rng = random.Random(23)
    x = terms.var("tkx", 256)
    y = terms.var("tky", 256)
    conjuncts = [
        terms.ult(terms.const(0, 256), terms.keccak(x)),
        terms.eq(
            terms.extract(255, 248, terms.keccak(terms.concat2(x, y))),
            terms.extract(255, 248, terms.keccak(terms.concat2(x, y))),
        ),
        terms.ult(terms.keccak(terms.concat2(x, y)), terms.bnot(terms.const(0, 256))),
    ]
    _check(conjuncts, _random_assignments([x, y], [], rng, 9))


def test_apply_raises_unsupported():
    x = terms.var("tux", 256)
    f = terms.apply_func("f", 256, x)
    with pytest.raises(tape_vm.TapeUnsupported):
        tape_vm.compile_tape([terms.eq(f, terms.const(1, 256))])


def test_cache_returns_same_object():
    x = terms.var("tcx", 256)
    conj = [terms.ult(x, terms.const(99, 256))]
    assert tape_vm.compile_tape(conj) is tape_vm.compile_tape(conj)


def test_deep_conjunction_uses_large_profile():
    rng = random.Random(29)
    x = terms.var("tdx", 256)
    y = terms.var("tdy", 256)
    acc = x
    conjuncts = []
    for k in range(30):
        acc = terms.add(terms.mul(acc, terms.const(k + 3, 256)), y)
        conjuncts.append(terms.ult(terms.const(k, 256), acc))
    compiled = tape_vm.compile_tape(conjuncts)
    assert compiled.tensors["profile"] == "large"
    _check(conjuncts, _random_assignments([x, y], [], rng, 11))
