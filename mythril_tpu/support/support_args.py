"""Global analysis flags.

The reference threads ~15 flags through a mutable ``Args`` singleton
(mythril/support/support_args.py:5-24).  This build keeps the same access
pattern for engine code but the object is a plain dataclass that the facade
constructs and *also* installs as the module-level default — device-side code
never reads it (flags are baked into traced programs as static arguments), so
the pjit-tracing hazard the survey warns about (SURVEY.md §5.6) does not arise.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class Args:
    solver_timeout: int = 10000  # ms, per query
    execution_timeout: int = 86400  # s, whole run
    create_timeout: int = 10  # s, creation tx
    max_depth: int = 128
    call_depth_limit: int = 3
    loop_bound: int = 3
    transaction_count: int = 2
    pruning_factor: Optional[float] = None
    unconstrained_storage: bool = False
    sparse_pruning: bool = False
    parallel_solving: bool = False  # TPU probe batches instead of z3 threads
    solver_log: Optional[str] = None
    use_integer_module: bool = True
    use_attack_as_target: bool = False
    enable_iprof: bool = False
    # write the benchmark plugin's series (JSON + SVG chart) to this path
    benchmark_path: Optional[str] = None
    # probe solver tuning
    probe_candidates: int = 48
    probe_rounds: int = 4
    probe_backend: str = "auto"  # auto | host | jax | cdcl (forced exact)
    keccak_backend: str = "auto"  # auto | jax | pallas (pallas on TPU when auto)
    # auto-backend break-even: dispatch to device when DAG-size x candidates
    # exceeds this (host evaluation below it is faster than one round trip).
    # Re-measured after the round-2 probe speedups (~4x faster host tiers):
    # on the tunneled chip per-query dispatch only pays past ~600k; the
    # device's real wins are frontier segments and merged batch dispatches
    device_probe_threshold: int = 600_000
    # frontier checkpointing
    checkpoint_path: Optional[str] = None
    resume_from: Optional[str] = None
    # deterministic replay only: GAS pushes the exact remaining gas instead
    # of a fresh symbol (conformance/concolic drivers; never symbolic runs)
    concrete_gas: bool = False
    # batched device-resident frontier interpreter (SURVEY.md §7.1)
    frontier: bool = False  # run message-call txs on the device frontier
    frontier_width: int = 64  # batch width B (paths held on device)
    # bypass the a-priori narrow gate (engine._device_worthwhile): used by
    # differential tests so frontier=True really exercises the device even
    # on deliberately tiny contracts
    frontier_force: bool = False
    # SPMD the frontier segment over all visible devices (path axis); the
    # engine shards automatically when >1 device is attached, padding the
    # batch width up to a device-count multiple with dead slots.  Composes
    # with the pipelined runner (chained dispatches run as one SPMD
    # program); --no-mesh is the single-device escape hatch
    frontier_mesh: bool = True
    # measure pure device-compute time of the first segment (chained
    # re-dispatch subtraction, tunnel-independent) into
    # FrontierStatistics().microbench — bench.py's device_microbench block
    frontier_microbench: bool = False
    # persistent SMT query cache (mythril_tpu/querycache): the in-process
    # LRU + reuse tiers run whenever query_cache is True; setting a dir
    # adds the disk-backed cross-run/cross-shard store
    query_cache: bool = True
    query_cache_dir: Optional[str] = None
    # partition each symbolic tx's selector space into one seed per
    # function-table entry + a complement seed (core/transaction/symbolic.
    # seed_message_call): same state space, but the work list starts
    # |selectors|+1 wide so the device frontier gets width up front
    multi_selector_seeding: bool = False
    # static bytecode pre-analysis (mythril_tpu/staticpass): CFG recovery +
    # abstract stack-height + taint reachability, gating detector hooks and
    # packed device events.  Over-approximate — the issue set is identical
    # either way; --no-staticpass is the escape hatch
    staticpass: bool = True
    # interprocedural layer on top of the base pass (value-set jump
    # refinement, function recovery, reachable-edge oracle, call graph);
    # --no-staticpass-interproc keeps the base passes only — the bench
    # parity gate compares exactly this toggle
    staticpass_interproc: bool = True
    # large-code frontier (mythril_tpu/frontier/code): per-code bucket
    # isolation (codes cluster into size classes, each dispatched with its
    # own compiled segment instead of one corpus-wide max bucket) plus
    # packed-code paging (codes beyond the residency budget keep only a
    # hot window resident; cold jumps fault to the host for a repack).
    # Issue-set-identical either way; --no-code-paging is the escape
    # hatch (and the parity baseline for bench.py --paging-compare)
    code_paging: bool = True
    # instruction-axis residency budget for packed-code paging: codes
    # whose instruction count exceeds the grown bucket of this value page
    # through a window of that size (0 disables paging, keeping bucket
    # isolation only)
    code_page_budget: int = 2048
    # pipelined frontier (mythril_tpu/frontier/pipeline): overlap device
    # segments with host harvest/solve via chained dispatch + a background
    # feasibility pool.  Issue-set-identical to the synchronous loop;
    # --no-pipeline is the escape hatch (and the parity baseline)
    pipeline: bool = True
    # abstract feasibility pre-filter (mythril_tpu/absdomain): vectorized
    # interval + known-bits pass ahead of the feasibility pool and the
    # solver fast path.  Sound (UNSAT verdicts only), issue-set-identical;
    # --no-prefilter is the escape hatch (and the parity baseline)
    prefilter: bool = True
    # device-resident SAT tier (mythril_tpu/devsolver): batched bit-blast
    # decision procedure between the pre-filter and the exact tiers.
    # UNSAT is exact, SAT models are concrete_eval-validated before trust,
    # UNKNOWN falls through; --no-devsolver is the escape hatch (and the
    # parity baseline for bench.py --devsolver-compare)
    devsolver: bool = True
    # admission: maximum free decision bits after known-bits/interval
    # narrowing for a query to enter the device tier
    devsolver_bit_budget: int = 64
    # search-kernel iteration budget per batch (budget lapse -> UNKNOWN)
    devsolver_iters: int = 2048
    # feasibility-pool worker threads (solves share one lock — the win is
    # moving solve latency off the harvest critical path, not parallelism)
    solver_workers: int = 2
    # harvest replay worker threads: terminal replays shard by owning
    # laser so no per-laser state is ever touched by two workers; results
    # commit in slot order, so issue sets are identical to the serial
    # sweep.  0 = serial escape hatch (and the parity baseline)
    harvest_workers: int = 4
    # persistent XLA compilation cache directory (None = the per-user
    # default under ~/.cache/mythril-tpu/xla; the
    # MYTHRIL_TPU_COMPILATION_CACHE env var disables with 0/off or
    # relocates with a path)
    compile_cache_dir: Optional[str] = None
    # one directory pinning BOTH persistent caches for service
    # deployments: query cache under <root>/querycache, XLA compile
    # cache under <root>/xla (facade/warm.resolve_cache_root); explicit
    # per-cache dirs win over the derivation
    cache_root: Optional[str] = None
    # coverage-guided adaptive exploration (mythril_tpu/adaptive): the
    # feedback controller that re-steers frontier dispatch slots at
    # uncovered reachable edges, resurrects budget-parked paths when
    # slots free, and targets concolic flips.  A scheduling-only
    # optimization — the issue set is bit-identical either way;
    # --no-adaptive is the escape hatch (and the parity baseline for
    # bench.py --adaptive-compare)
    adaptive: bool = True
    # terminate exploration once reachable-edge/instruction coverage
    # reaches this percent (or all explored codes plateau below it):
    # the "explore to a coverage bar" request contract.  None = explore
    # to the transaction/time budget as before
    coverage_target: Optional[float] = None
    # flight deck (mythril_tpu/observability): heartbeat JSONL of sampled
    # queue depths, sampler period, flight-recorder bundle directory, and
    # the watchdog deadline (seconds without a completed segment before a
    # hang bundle is dumped; None disables the watchdog)
    heartbeat_out: Optional[str] = None
    heartbeat_interval: float = 0.5
    flight_recorder: Optional[str] = None
    watchdog_deadline: Optional[float] = None


args = Args()
