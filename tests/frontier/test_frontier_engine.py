"""Differential tests: device frontier vs host engine on real analyses.

The host engine is the oracle (VERDICT.md round-1 item 1): the same contract
analyzed with ``args.frontier`` on and off must produce the same issues.
"""

import pytest

from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.support.support_args import args as global_args


def analyze(code_hex: str, tx_count=1, modules=None, frontier=False):
    reset_callback_modules()
    # the per-(address, bytecode) issue cache deliberately survives module
    # resets (reference base.py:70-95); differential runs re-analyze the
    # same bytecode, so clear it between runs
    from mythril_tpu.analysis.module.loader import ModuleLoader

    for m in ModuleLoader().get_detection_modules():
        if hasattr(m, "cache"):
            m.cache.clear()
    old = global_args.frontier
    old_force = global_args.frontier_force
    global_args.frontier = frontier
    # differential fixtures are deliberately tiny: bypass the a-priori
    # narrow gate so frontier=True really exercises the device
    global_args.frontier_force = frontier
    try:
        sym = SymExecWrapper(
            bytes.fromhex(code_hex),
            address=0x0901D12E,
            strategy="dfs",
            transaction_count=tx_count,
            execution_timeout=60,
            modules=modules,
        )
        return fire_lasers(sym, white_list=modules)
    finally:
        global_args.frontier = old
        global_args.frontier_force = old_force


def issue_keys(issues):
    return sorted(
        (i.swc_id, i.address, i.function, i.severity) for i in issues
    )


# dispatcher prelude: selector(kill()=0x41c0e1b5) -> JUMPDEST at 0x14=20
DISPATCH = "60003560e01c6341c0e1b5146014576000" + "6000fd" + "5b"


@pytest.mark.parametrize("frontier", [False, True])
def test_unprotected_selfdestruct(frontier):
    issues = analyze(
        DISPATCH + "33ff", modules=["AccidentallyKillable"], frontier=frontier
    )
    assert len(issues) == 1
    issue = issues[0]
    assert issue.swc_id == "106"
    assert issue.function == "kill()"
    step = issue.transaction_sequence["steps"][-1]
    assert step["input"].startswith("0x41c0e1b5")


def test_differential_selfdestruct_matches_host():
    host = analyze(DISPATCH + "33ff", modules=["AccidentallyKillable"])
    dev = analyze(
        DISPATCH + "33ff", modules=["AccidentallyKillable"], frontier=True
    )
    assert issue_keys(host) == issue_keys(dev)


def test_differential_clean_contract():
    code = "602a60005500"  # store 42 at slot 0, stop
    assert analyze(code, frontier=True) == []


def test_differential_exception_invalid():
    host = analyze(DISPATCH + "fe", modules=["Exceptions"])
    dev = analyze(DISPATCH + "fe", modules=["Exceptions"], frontier=True)
    assert issue_keys(host) == issue_keys(dev)
    assert len(dev) == 1
    assert dev[0].swc_id == "110"


def test_differential_tx_origin():
    body = "323314601b5700" "5b00"
    host = analyze(DISPATCH + body, modules=["TxOrigin"])
    dev = analyze(DISPATCH + body, modules=["TxOrigin"], frontier=True)
    assert issue_keys(host) == issue_keys(dev)
    assert len(dev) == 1
    assert dev[0].swc_id == "115"


def test_differential_integer_overflow():
    body = "600435" "6001" "01" "6000" "55" "00"
    host = analyze(DISPATCH + body, modules=["IntegerArithmetics"])
    dev = analyze(DISPATCH + body, modules=["IntegerArithmetics"], frontier=True)
    assert issue_keys(host) == issue_keys(dev)
    assert len(dev) >= 1
    assert dev[0].swc_id == "101"


def test_differential_timestamp():
    body = "426064" "11" "601c57" "00" "5b00"
    host = analyze(DISPATCH + body, modules=["PredictableVariables"])
    dev = analyze(
        DISPATCH + body, modules=["PredictableVariables"], frontier=True
    )
    assert issue_keys(host) == issue_keys(dev)


def test_multi_tx_killbilly_exploit():
    """2-tx storage-gated selfdestruct: tx reseeding + storage encode must
    chain through the device frontier (bench.py's headline workload)."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).parents[2]))
    import bench

    old = global_args.frontier
    old_force = global_args.frontier_force
    global_args.frontier = True
    global_args.frontier_force = True
    try:
        _sym, issues, _wall = bench.run_analysis("auto")
    finally:
        global_args.frontier = old
        global_args.frontier_force = old_force
    bench.check_recall(issues)


def test_arena_const_value_full_width():
    """Regression: numpy int32 widths cannot shift 1 << 256 (C long)."""
    import numpy as np

    from mythril_tpu.frontier.arena import HostArena

    arena = HostArena(64)
    row = arena.const_row((1 << 256) - 1, 256)
    assert isinstance(arena.width[row], np.int32) or arena.width.dtype == np.int32
    assert arena.const_value(row) == (1 << 256) - 1


def test_mload_straddling_stored_word_parks():
    """Soundness regression: MLOAD at 16 over a word stored at 0 must not
    read zero on the device (exact-address miss); the path parks and the
    host engine computes the straddled composite, keeping the feasible
    selfdestruct branch alive."""
    # mstore(0, calldataload(0)); jumpi(0x22, mload(16)); stop; jumpdest caller selfdestruct
    body = "600035" "600052" "601051" "602257" "00" "5b33ff"
    host = analyze(DISPATCH + body, modules=["AccidentallyKillable"])
    dev = analyze(DISPATCH + body, modules=["AccidentallyKillable"], frontier=True)
    assert issue_keys(host) == issue_keys(dev)
    assert len(dev) == 1 and dev[0].swc_id == "106"


def test_sha3_straddling_stored_word_parks():
    """Same straddle hazard through the SHA3 word gather.

    The branch guard is ``sha3(16, 32) != keccak(0^32)``: a device that
    wrongly hashes the exact-miss zero word folds the guard to false and
    never reaches the selfdestruct, while the straddled composite (host,
    or a parked path) is satisfiable with nonzero calldata."""
    k0 = "290decd9548b62a8d60345a988386fc84ba6bc95484008f6362f93160ef3e563"
    # mstore(0, calldataload(0)); h = sha3(16, 32);
    # jumpi(0x47, iszero(eq(h, K0)) == 0 ? ... ) -> iszero(eq) as guard
    body = "600035" + "600052" + "6020601020" + "7f" + k0 + "14" + "15" + "604757" + "00" + "5b33ff"
    host = analyze(DISPATCH + body, modules=["AccidentallyKillable"])
    dev = analyze(DISPATCH + body, modules=["AccidentallyKillable"], frontier=True)
    assert issue_keys(host) == issue_keys(dev)
    assert len(dev) == 1 and dev[0].swc_id == "106"


def test_parked_call_body_falls_back_to_host():
    # CALL is not device-executable: the path parks and the host engine
    # finishes it; issues must match the pure-host run
    body = "6000" "6000" "6000" "6000" "6064" "33" "61ffff" "f1" "00"
    host = analyze(DISPATCH + body)
    dev = analyze(DISPATCH + body, frontier=True)
    assert issue_keys(host) == issue_keys(dev)


@pytest.mark.parametrize(
    "fixture,module,swc",
    [
        ("suicide.sol.o", "AccidentallyKillable", "106"),
        ("exceptions.sol.o", "Exceptions", "110"),
        ("origin.sol.o", "TxOrigin", "115"),
        ("ether_send.sol.o", "EtherThief", "105"),
    ],
)
def test_differential_corpus_contracts(fixture, module, swc):
    """Frontier-vs-host issue parity across distinct detectors on real solc
    output (the corpus sweep's recall contracts; solc code is MSTORE/JUMPI
    dense, exercising event-buffer pressure and fork-grant coupling)."""
    import pathlib

    path = pathlib.Path("/root/reference/tests/testdata/inputs") / fixture
    if not path.exists():
        pytest.skip("reference corpus not mounted")
    code = path.read_text().strip().replace("0x", "")
    host = analyze(code, tx_count=2, modules=[module])
    dev = analyze(code, tx_count=2, modules=[module], frontier=True)
    assert issue_keys(host) == issue_keys(dev)
    assert any(i.swc_id == swc for i in dev)


def test_verdict_memos_gate_device_entry():
    """Narrow-marked codes skip narrow drains but a wide seed set still
    goes (width comes from many seeds); SLOW-marked codes (throughput
    bail) are skipped even wide — re-draining just re-pays a proven
    loss."""
    from mythril_tpu.frontier import engine as E

    class _Code:
        def __init__(self, bytecode):
            self.bytecode = bytecode

    class _Env:
        def __init__(self, code):
            self.code = code

    class _GS:
        def __init__(self, code):
            self.environment = _Env(code)

    code = _Code(b"\x60\x00" * 40)
    eng = E.FrontierEngine.__new__(E.FrontierEngine)
    eng.caps = E.Caps(B=64)
    pairs = [(None, _GS(code))]
    wide = [(None, _GS(code)) for _ in range(eng.caps.MIN_LIVE)]
    key = E._code_key(code)
    old_force = E.args.frontier_force
    E.args.frontier_force = False
    try:
        E._NARROW_CODES.add(key)
        assert not eng._device_worthwhile(pairs)
        assert eng._device_worthwhile(wide)  # width bypasses NARROW
        E._NARROW_CODES.discard(key)
        E._SLOW_CODES.add(key)
        assert not eng._device_worthwhile(pairs)
        assert not eng._device_worthwhile(wide)  # SLOW outranks width
        # a mixed batch with an unmarked member still goes
        other = _Code(b"\x60\x01" * 40)
        assert eng._device_worthwhile(wide + [(None, _GS(other))])
    finally:
        E._NARROW_CODES.discard(key)
        E._SLOW_CODES.discard(key)
        E.args.frontier_force = old_force


def test_break_paths_return_queued_seeds_to_work_list():
    """Seeds queued beyond the batch width when a run ends on a break path
    (slow-bail/timeout/arena) must land back on their laser's work list —
    regression for silently vanished exploration states."""
    from mythril_tpu.frontier import engine as E
    from mythril_tpu.frontier.state import Caps

    # a tiny batch (B=2) with 5 eligible fresh seeds and an immediate
    # execution timeout: the loop breaks on the timeout path with seeds
    # still queued
    from mythril_tpu.analysis.symbolic import SymExecWrapper

    reset_callback_modules()
    old = (global_args.frontier, global_args.frontier_force,
           global_args.frontier_mesh)
    global_args.frontier = False
    global_args.frontier_force = True
    # single-device: mesh padding would widen B=2 up to the device count,
    # giving every seed a slot — nothing would queue and this fast contract
    # finishes before the break path this test exists to exercise
    global_args.frontier_mesh = False
    try:
        sym = SymExecWrapper(
            bytes.fromhex(DISPATCH + "33ff"),
            address=0x0901D12E,
            strategy="dfs",
            transaction_count=1,
            execution_timeout=60,
            modules=["AccidentallyKillable"],
            defer_exec=True,
        )
        laser = sym.laser
        from mythril_tpu.core.transaction.symbolic import seed_message_call

        laser.open_states = [sym.deferred_world_state]
        seed_message_call(laser, 0x0901D12E)
        seed = laser.work_list[0]
        import copy as _c

        laser.work_list.extend(_c.copy(seed) for _ in range(4))
        n_before = len(laser.work_list)
        assert n_before == 5

        engine = E.FrontierEngine(laser, Caps(B=2))
        laser.execution_timeout = 0  # loop hits the timeout break instantly
        engine.drain_work_list()
        # every seed must be back (order/form may differ: parked carriers)
        assert len(laser.work_list) == n_before, (
            f"{n_before - len(laser.work_list)} seeds vanished"
        )
    finally:
        (global_args.frontier, global_args.frontier_force,
         global_args.frontier_mesh) = old


def test_host_step_rate_requires_samples():
    """host_step_rate is None until the warmup sample count is reached,
    then reports steps/sec over the accumulated iteration wall."""
    from mythril_tpu.core import svm as svm_mod

    class _L:
        host_step_rate = svm_mod.LaserEVM.host_step_rate
        _host_steps = 0
        _host_step_secs = 0.0

    laser = _L()
    assert laser.host_step_rate() is None
    laser._host_steps = svm_mod._FRONTIER_WARMUP_STEPS
    laser._host_step_secs = float(svm_mod._FRONTIER_WARMUP_STEPS) / 500.0
    assert abs(laser.host_step_rate() - 500.0) < 1e-6
