"""Taint-bit registry self-checks (frontier/taint.py register())."""

import pytest

from mythril_tpu.frontier import taint


class _AnnoA:
    pass


class _AnnoB:
    pass


@pytest.fixture
def _scratch_registry():
    """Run against a copy so the process-global registry (already populated
    by detector imports) is untouched."""
    saved_f = dict(taint._factories)
    saved_m = list(taint._matchers)
    saved_s = dict(taint._singletons)
    yield
    taint._factories.clear()
    taint._factories.update(saved_f)
    taint._matchers[:] = saved_m
    taint._singletons.clear()
    taint._singletons.update(saved_s)


def test_register_rejects_non_single_bit(_scratch_registry):
    for bad in (0, -1, 3, 6, 1 << 8 | 1):
        with pytest.raises(ValueError, match="single set bit"):
            taint.register(bad, _AnnoA, lambda a: False)


def test_register_same_factory_is_idempotent(_scratch_registry):
    bit = 1 << 20
    taint.register(bit, _AnnoA, lambda a: isinstance(a, _AnnoA))
    taint.register(bit, _AnnoA, lambda a: isinstance(a, _AnnoA))  # no raise
    assert taint._factories[bit] is _AnnoA
    # the matcher list must not grow on the no-op re-registration
    assert sum(1 for b, _ in taint._matchers if b == bit) == 1


def test_register_different_factory_raises(_scratch_registry):
    bit = 1 << 21
    taint.register(bit, _AnnoA, lambda a: isinstance(a, _AnnoA))
    with pytest.raises(ValueError, match="different factory"):
        taint.register(bit, _AnnoB, lambda a: isinstance(a, _AnnoB))


def test_unknown_bit_synthesizes_nothing(_scratch_registry):
    # seeding an unregistered bit is harmless: the walker synthesizes no
    # annotation for it (module disabled -> its bit is inert)
    unknown = 1 << 22
    assert taint.annotations_for_mask(unknown) == ()
    assert taint.annotations_for_mask(0) == ()


def test_registered_bit_synthesizes_singleton(_scratch_registry):
    bit = 1 << 23
    taint.register(bit, _AnnoA, lambda a: isinstance(a, _AnnoA))
    (first,) = taint.annotations_for_mask(bit)
    (second,) = taint.annotations_for_mask(bit)
    assert isinstance(first, _AnnoA)
    assert first is second  # singleton, never re-instantiated


def test_mask_for_annotations_round_trip(_scratch_registry):
    bit = 1 << 24
    taint.register(bit, _AnnoA, lambda a: isinstance(a, _AnnoA))
    assert taint.mask_for_annotations([_AnnoA()]) == bit
    assert taint.mask_for_annotations([_AnnoB()]) == 0


def test_source_opcodes_cover_all_seeded_bits():
    # the static pass keys may_reach on SOURCE_OPCODES: every seedable bit
    # must have a source opcode or its flows would be invisible to the gate
    for bit in taint.SEEDED_BITS:
        assert bit in taint.SOURCE_OPCODES
