"""Per-instruction dispatch tables: bytecode -> dense device tables.

The host pre-decodes the instruction stream once per contract (the analogue of
``Disassembly`` feeding the host engine's dispatch,
mythril_tpu/core/svm.py:274-317) into flat numpy tables indexed by
*instruction index* (not byte address — matching the host engine's pc
convention, reference mythril/laser/ethereum/svm.py:351):

  * ``fam``      handler family (``ops.F_*``) for the lax.switch dispatch
  * ``aux``      family-specific immediate (binop code, PUSH const row, ...)
  * ``arity``    required stack inputs (underflow -> exceptional halt)
  * ``gmin/gmax``  static gas bounds per opcode (dynamic parts added by
                 handlers, mirroring instruction_data.get_opcode_gas)
  * ``event``    whether executing this instruction records an event for the
                 host walker (always-evented ops + every opcode the engine
                 has detector hooks on)
  * ``addr``     byte address of the instruction (for PC, reports)
  * ``jumpmap``  byte address -> instruction index of a JUMPDEST (-1 if not)
  * ``loop_id``  dense id per JUMPDEST for loop-bound counting (-1 otherwise)
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set

import numpy as np

from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier.arena import HostArena

# ops that always record an event regardless of hooks: the walker needs them
# to keep carrier storage/constraints exact between hook sites.  MSTORE is
# NOT here: carrier memory is rebuilt from the device's word table at
# terminals/parks (records.snapshot_slot "mem" + walker._restore_memory),
# so memory writes — the densest op class in solc output — only event when
# a hook needs them (and the user_assertions panic gate suppresses even
# those for concrete non-panic values, see ``value_gate_opcodes``).
_ALWAYS_EVENT = {
    "JUMPI", "SSTORE", "SLOAD", "MSTORE8",
    "STOP", "RETURN", "REVERT", "SELFDESTRUCT", "INVALID", "ASSERT_FAIL",
}

_BINOP = {
    "ADD": O.A_ADD, "SUB": O.A_SUB, "MUL": O.A_MUL, "DIV": O.A_UDIV,
    "SDIV": O.A_SDIV, "MOD": O.A_UREM, "SMOD": O.A_SREM, "AND": O.A_AND,
    "OR": O.A_OR, "XOR": O.A_XOR, "EXP": O.A_EXP,
    "SHL": O.A_SHL, "SHR": O.A_LSHR, "SAR": O.A_ASHR,
}
_SHIFT_OPS = {"SHL", "SHR", "SAR"}  # pop order: (shift, value)

_CMP = {
    "LT": O.A_ULT, "GT": O.A_UGT, "SLT": O.A_SLT, "SGT": O.A_SGT, "EQ": O.A_EQ,
}

# env slots in the per-path context vector (state.ctx)
(
    CTX_CALLER, CTX_ORIGIN, CTX_CALLVALUE, CTX_ADDRESS, CTX_CDSIZE,
    CTX_BALANCES, CTX_STORAGE, CTX_GASPRICE, CTX_COINBASE, CTX_TIMESTAMP,
    CTX_NUMBER, CTX_DIFFICULTY, CTX_GASLIMIT, CTX_CHAINID, CTX_BASEFEE,
    CTX_SEED,
) = range(16)
CTX_W = 16

_ENVPUSH = {
    "CALLER": CTX_CALLER, "ORIGIN": CTX_ORIGIN, "CALLVALUE": CTX_CALLVALUE,
    "ADDRESS": CTX_ADDRESS, "CALLDATASIZE": CTX_CDSIZE,
    "GASPRICE": CTX_GASPRICE, "COINBASE": CTX_COINBASE,
    "TIMESTAMP": CTX_TIMESTAMP, "NUMBER": CTX_NUMBER,
    "DIFFICULTY": CTX_DIFFICULTY, "PREVRANDAO": CTX_DIFFICULTY,
    "GASLIMIT": CTX_GASLIMIT, "CHAINID": CTX_CHAINID, "BASEFEE": CTX_BASEFEE,
}


class CodeTables:
    def __init__(
        self,
        instruction_list: List,
        arena: HostArena,
        hooked_opcodes: Optional[Iterable[str]] = None,
        code_size: Optional[int] = None,
        conc_nop_opcodes: Optional[Iterable[str]] = None,
        value_gate_opcodes: Optional[Iterable[str]] = None,
        static_summary=None,
    ):
        from mythril_tpu.support.opcodes import OPCODES

        hooked: Set[str] = set(hooked_opcodes or ())
        # hooked opcodes whose every hook is a declared no-op on all-concrete
        # operands (module concrete_nop_hooks): evented, but the device
        # suppresses the event when operand concreteness proves the no-op
        conc_nop: Set[str] = set(conc_nop_opcodes or ()) - _ALWAYS_EVENT
        # MSTORE panic gate (module value_gated_hooks): event only when the
        # stored value is concrete with the solc Panic(uint256) selector in
        # its top 32 bits — the single case the hook observes (symbolic
        # values no-op there too)
        val_gate: Set[str] = set(value_gate_opcodes or ()) & {"MSTORE"}
        n = len(instruction_list)
        self.n = n
        self.instruction_list = instruction_list
        self.fam = np.zeros(n + 1, np.int32)  # +1: implicit STOP off the end
        self.aux = np.zeros(n + 1, np.int32)
        self.arity = np.zeros(n + 1, np.int32)
        self.gmin = np.zeros(n + 1, np.int32)
        self.gmax = np.zeros(n + 1, np.int32)
        self.event = np.zeros(n + 1, bool)
        self.concskip = np.zeros(n + 1, bool)
        self.valgate = np.zeros(n + 1, bool)
        self.addr = np.zeros(n + 1, np.int32)
        self.opcode_names: List[str] = []

        max_addr = max((ins.address for ins in instruction_list), default=0)
        self.jumpmap = np.full(max_addr + 2, -1, np.int32)
        self.loop_id = np.full(n + 1, -1, np.int32)
        n_loops = 0

        # static pre-analysis (mythril_tpu/staticpass): statically
        # unreachable instructions leave the packed event set (they can
        # never execute, so no walker replay depends on them) and their
        # JUMPDESTs claim no loop slot (the _LOOPS_CAP budget goes to
        # code that can actually loop).  jumpmap keeps EVERY JUMPDEST —
        # dynamic jump validity is the device's own check, not the
        # pass's.  ``static_target`` exports statically resolved
        # JUMP/JUMPI destinations (instruction index, -1 = dynamic) so
        # device/host consumers can skip the jumpmap fallback path.
        reach = None
        if (
            static_summary is not None
            and static_summary.n_instructions == n
        ):
            reach = static_summary.instr_reachable
        self.static_target = np.full(n + 1, -1, np.int32)
        events_pruned = 0
        jumpi_events_pruned = 0

        for i, ins in enumerate(instruction_list):
            name = ins.opcode
            self.opcode_names.append(name)
            self.addr[i] = ins.address
            info = OPCODES.get(name)
            if info is not None:
                _, arity, _, g0, g1 = info
                self.arity[i], self.gmin[i], self.gmax[i] = arity, g0, g1
            reachable = reach is None or bool(reach[i])
            event = name in _ALWAYS_EVENT or name in hooked
            self.event[i] = event and reachable
            if event and not reachable:
                events_pruned += 1
                if name == "JUMPI":
                    jumpi_events_pruned += 1
            self.concskip[i] = name in conc_nop
            self.valgate[i] = name in val_gate
            fam, aux = self._classify(ins, arena, code_size)
            self.fam[i], self.aux[i] = fam, aux
            if name == "JUMPDEST":
                self.jumpmap[ins.address] = i
                if reachable:
                    self.loop_id[i] = n_loops
                    n_loops += 1
            if reach is not None and reachable:
                self.static_target[i] = static_summary.static_target[i]

        if events_pruned:
            from mythril_tpu.observability import get_registry

            get_registry().counter("staticpass.events_pruned").inc(
                events_pruned
            )
            if jumpi_events_pruned:
                get_registry().counter(
                    "staticpass.jumpi_events_pruned"
                ).inc(jumpi_events_pruned)

        # reachable-edge oracle accounting: JUMPI edges the interprocedural
        # layer proved dead (constant-folded condition or invalid/unreachable
        # destination).  The event bit itself stays at instruction
        # granularity — a reachable JUMPI with one dead edge still events
        # for the walker — but the dead-edge count is what the pruning
        # parity gate and the drift doctor watch.
        if (
            reach is not None
            and getattr(static_summary, "edge_taken_live", None) is not None
        ):
            taken_live = static_summary.edge_taken_live
            fall_live = static_summary.edge_fall_live
            edges_dead = 0
            for i, ins in enumerate(instruction_list):
                if ins.opcode == "JUMPI":
                    edges_dead += int(not taken_live[i]) + int(not fall_live[i])
            if edges_dead:
                from mythril_tpu.observability import get_registry

                get_registry().counter(
                    "staticpass.jumpi_edges_pruned"
                ).inc(edges_dead)

        # implicit STOP past the end of code (reference svm.py:281-284)
        self.fam[n] = O.F_STOP
        self.event[n] = True
        self.addr[n] = max_addr + 1
        self.opcode_names.append("STOP")
        self.n_loops = max(n_loops, 1)

    def _classify(self, ins, arena: HostArena, code_size: Optional[int]):
        name = ins.opcode
        if name.startswith("PUSH"):
            value = ins.arg_int or 0
            return O.F_PUSH, arena.const_row(value, 256)
        if name.startswith("DUP"):
            return O.F_DUP, int(name[3:])
        if name.startswith("SWAP"):
            return O.F_SWAP, int(name[4:])
        if name.startswith("LOG"):
            return O.F_LOG, int(name[3:])
        if name in _BINOP:
            # aux low bits: arena op; bit 8: operands pop as (shift, value)
            swap = 256 if name in _SHIFT_OPS else 0
            return O.F_BINOP, _BINOP[name] | swap
        if name in _CMP:
            return O.F_CMP, _CMP[name]
        if name in _ENVPUSH:
            return O.F_ENVPUSH, _ENVPUSH[name]
        simple = {
            "STOP": (O.F_STOP, 0),
            "POP": (O.F_POP, 0),
            "ISZERO": (O.F_ISZERO, 0),
            "NOT": (O.F_NOTOP, 0),
            "CALLDATALOAD": (O.F_CALLDATALOAD, 0),
            "BALANCE": (O.F_BALANCE, 0),
            "SELFBALANCE": (O.F_SELFBALANCE, 0),
            "SHA3": (O.F_SHA3, 0),
            "KECCAK256": (O.F_SHA3, 0),
            "MLOAD": (O.F_MLOAD, 0),
            "MSTORE": (O.F_MSTORE, 0),
            "SLOAD": (O.F_SLOAD, 0),
            "SSTORE": (O.F_SSTORE, 0),
            "JUMP": (O.F_JUMP, 0),
            "JUMPI": (O.F_JUMPI, 0),
            "JUMPDEST": (O.F_JUMPDEST, 0),
            "GAS": (O.F_GASPUSH, 0),
            "MSIZE": (O.F_MSIZE, 0),
            "RETURN": (O.F_RETURN, 0),
            "REVERT": (O.F_RETURN, 1),
            "SELFDESTRUCT": (O.F_SELFDESTRUCT, 0),
            "INVALID": (O.F_INVALID, 0),
            "ASSERT_FAIL": (O.F_INVALID, 0),
            "SIGNEXTEND": (O.F_SIGNEXTEND, 0),
            "BYTE": (O.F_BYTEOP, 0),
            "ADDMOD": (O.F_ADDMODOP, O.A_ADDMOD),
            "MULMOD": (O.F_ADDMODOP, O.A_MULMOD),
        }
        if name == "PC":
            return O.F_PUSH, arena.const_row(ins.address, 256)
        if name == "CODESIZE" and code_size is not None:
            return O.F_PUSH, arena.const_row(code_size, 256)
        if name in simple:
            return simple[name]
        # everything else (CALL family, CREATE, copies, EXTCODE*, BLOCKHASH,
        # RETURNDATA*, ...) parks the path for the host engine
        return O.F_PARK, 0

    def size_bucket(self) -> tuple:
        """(instr_cap, addr_cap, loops_cap) — padded sizes so one compiled
        segment program serves every contract in the same bucket.  Base caps
        fit EIP-170 runtime code (24576 bytes); larger inputs (initcode,
        arbitrary files) grow the bucket instead of crashing.

        Under packed-code paging the instruction axis is capped at the
        residency budget: a paged code's device tables hold only the
        resident window, so an oversized code stops growing the bucket
        (pc stays the TRUE instruction index; the window check in step.py
        faults non-resident pcs to the host for a repack)."""
        instr_cap = _grow(_INSTR_BASE, _INSTR_GROWTH, self.fam.shape[0])
        budget = page_budget()
        if budget is not None and instr_cap > budget:
            instr_cap = budget
        addr_cap = _grow(_ADDR_BASE, _ADDR_GROWTH, self.jumpmap.shape[0])
        return instr_cap, addr_cap, _LOOPS_CAP

    def full_instr_cap(self) -> int:
        """Instruction-axis cap covering the WHOLE code (paging ignored) —
        the coverage-plane axis, which is indexed by true pc."""
        return _grow(_INSTR_BASE, _INSTR_GROWTH, self.fam.shape[0])

    def is_paged(self) -> bool:
        """True when the code's instruction axis exceeds the residency
        budget, i.e. its device tables hold a window, not the whole code."""
        budget = page_budget()
        return budget is not None and self.fam.shape[0] > budget

    def padded_device_tables(self, bucket: Optional[tuple] = None,
                             window_base: int = 0):
        """CodeDev-shaped numpy arrays padded to the size bucket; the pad
        region dispatches F_STOP (unreachable: pc never exceeds n).

        ``window_base`` selects the resident window of a paged code: the
        instruction-axis tables hold rows [window_base, window_base +
        instr_cap) and the device subtracts the base before every gather.
        jumpmap is NOT windowed (it is byte-address-indexed and maps to
        TRUE instruction indices, so jumps into cold spans resolve and
        then fault at the next dispatch).

        JUMPDESTs beyond the loops cap get loop_id -1 (no loop bound for
        them, rather than aliasing counters and killing loop-free paths);
        max_depth and the segment step cap still bound those paths."""
        instr_cap, addr_cap, loops_cap = bucket or self.size_bucket()

        def pad1(a, cap, fill, base=0):
            seg = a[base:base + cap]
            out = np.full(cap, fill, a.dtype)
            out[: seg.shape[0]] = seg
            return out

        b = int(window_base)
        loop_id = np.where(self.loop_id >= loops_cap, -1, self.loop_id)
        return (
            pad1(self.fam, instr_cap, O.F_STOP, b),
            pad1(self.aux, instr_cap, 0, b),
            pad1(self.arity, instr_cap, 0, b),
            pad1(self.gmin, instr_cap, 0, b),
            pad1(self.gmax, instr_cap, 0, b),
            pad1(self.event, instr_cap, True, b),
            pad1(self.jumpmap, addr_cap, -1),
            pad1(loop_id, instr_cap, -1, b),
            pad1(self.concskip, instr_cap, False, b),
            pad1(self.valgate, instr_cap, False, b),
        )


# bucket-growth bases shared by every sizing path (CodeTables.size_bucket,
# multi_size_bucket, bucket_hint) — ONE set of constants so a tuning change
# cannot desynchronize the cooperative driver's floor from the real bucket
# (a mismatch silently reintroduces mid-sweep XLA recompiles)
_INSTR_BASE, _INSTR_GROWTH = 512, 4
_ADDR_BASE, _ADDR_GROWTH = 32768, 2
_CODE_GROWTH = 8
_LOOPS_CAP = 512


def _grow(base: int, factor: int, need: int) -> int:
    cap = base
    while cap < need:
        cap *= factor
    return cap


def page_budget() -> Optional[int]:
    """Instruction-axis residency budget (a grown bucket size), or None
    when packed-code paging is off (--no-code-paging).  Codes whose
    instruction axis exceeds this keep only a window of that many rows
    resident on device; cold spans page in via host repacks."""
    from mythril_tpu.support.support_args import args

    if not getattr(args, "code_paging", True):
        return None
    budget = int(getattr(args, "code_page_budget", 0) or 0)
    if budget <= 0:
        return None
    return _grow(_INSTR_BASE, _INSTR_GROWTH, budget)


def _hint_size_bucket(instruction_list: List) -> tuple:
    """CodeTables.size_bucket computed from the raw instruction list (no
    table build) — MUST mirror size_bucket exactly or the cooperative
    floor desynchronizes from the real bucket (mid-sweep recompiles)."""
    instr_cap = _grow(
        _INSTR_BASE, _INSTR_GROWTH, len(instruction_list) + 1
    )  # +1: implicit trailing STOP
    budget = page_budget()
    if budget is not None and instr_cap > budget:
        instr_cap = budget
    max_addr = max((ins.address for ins in instruction_list), default=0)
    addr_cap = _grow(_ADDR_BASE, _ADDR_GROWTH, max_addr + 2)
    return instr_cap, addr_cap, _LOOPS_CAP


def bucket_hint(instruction_lists: List[List]) -> tuple:
    """(code_cap, instr_cap, addr_cap, loops_cap) covering these codes
    WITHOUT building tables — the cooperative driver pins this as the
    bucket floor so every tx round of a sweep shares one compiled segment
    program even as the live code set shrinks."""
    code_cap = _grow(1, _CODE_GROWTH, len(instruction_lists))
    instr_cap, addr_cap = _INSTR_BASE, _ADDR_BASE
    for instruction_list in instruction_lists:
        ic, ac, _lc = _hint_size_bucket(instruction_list)
        instr_cap, addr_cap = max(instr_cap, ic), max(addr_cap, ac)
    return code_cap, instr_cap, addr_cap, _LOOPS_CAP


def bucket_hint_classes(instruction_lists: List[List]) -> List[tuple]:
    """Per-class bucket floors for a cooperative sweep: the codes cluster
    by their own size bucket (same rule as ``bucket_classes``), and each
    class gets a (code_cap, instr_cap, addr_cap, loops_cap) floor sized
    for ITS members only — tiny contracts stop compiling giant programs
    because one creation-heavy outlier rides the same sweep."""
    groups: Dict[tuple, int] = {}
    for instruction_list in instruction_lists:
        key = _hint_size_bucket(instruction_list)
        groups[key] = groups.get(key, 0) + 1
    return [
        (_grow(1, _CODE_GROWTH, n),) + key
        for key, n in sorted(groups.items())
    ]


def bucket_classes(tables: List["CodeTables"]) -> List[tuple]:
    """Cluster codes into bucket classes: members sharing the same
    per-code ``size_bucket`` form one class with its own
    (code_cap, instr_cap, addr_cap, loops_cap).  The growth factors are
    geometric, so a mixed corpus lands in a handful of classes — and a
    creation-heavy outlier pays for its own axes instead of taxing every
    small code in the batch.  Returns [(bucket, member_indices)] sorted
    small-to-large (deterministic across rounds of a sweep)."""
    groups: Dict[tuple, List[int]] = {}
    for i, t in enumerate(tables):
        groups.setdefault(t.size_bucket(), []).append(i)
    return [
        ((_grow(1, _CODE_GROWTH, len(idxs)),) + key, idxs)
        for key, idxs in sorted(groups.items())
    ]


def visited_instr_cap(tables: List["CodeTables"]) -> int:
    """Coverage-plane instruction axis: the FULL (unpaged) cap over the
    members.  Coverage is indexed by true pc, so the planes must cover
    whole codes even when the dispatch tables hold only a window."""
    return max((t.full_instr_cap() for t in tables), default=_INSTR_BASE)


def pad_waste_pct(tables: List["CodeTables"], bucket: tuple) -> float:
    """Percent of the bucket's [C, instr_cap] instruction plane that is
    padding (code slots beyond the corpus count entirely; per-member rows
    beyond the code's resident span).  The number the large-code tail is
    about: one outlier inflating a shared bucket shows up here directly."""
    code_cap, instr_cap, _ac, _lc = bucket
    if not tables or code_cap <= 0 or instr_cap <= 0:
        return 0.0
    used = sum(min(t.fam.shape[0], instr_cap) for t in tables)
    total = code_cap * instr_cap
    return 100.0 * (1.0 - used / total)


def multi_size_bucket(tables: List["CodeTables"]) -> tuple:
    """(code_cap, instr_cap, addr_cap, loops_cap) covering every table.

    The code axis buckets at 1/8/32/... so one compiled segment serves any
    corpus batch of similar shape; instr/addr caps are the max over members
    (each member's own bucket, so a corpus of small contracts stays small)."""
    code_cap = _grow(1, _CODE_GROWTH, len(tables))
    instr_cap = addr_cap = loops_cap = 0
    for t in tables:
        ic, ac, lc = t.size_bucket()
        instr_cap, addr_cap, loops_cap = (
            max(instr_cap, ic), max(addr_cap, ac), max(loops_cap, lc)
        )
    return code_cap, instr_cap, addr_cap, loops_cap


def stacked_device_tables(tables: List["CodeTables"], bucket: tuple,
                          page_bases: Optional[List[int]] = None):
    """Stack per-code padded tables into the [C, ...] CodeDev arrays the
    segment consumes — the dispatch tables become per-path inputs via one
    [B] gather per table (multi-code frontier batching: paths from different
    contracts share a single wide device segment).  Pad codes beyond
    ``len(tables)`` dispatch F_STOP everywhere (unreachable: code_id is
    always a real index).

    ``page_bases`` (one window start per member, default all 0) windows
    paged codes; the per-code starts ride along as the trailing ``pbase``
    [C] column so the device can subtract them before every table gather."""
    code_cap, instr_cap, addr_cap, loops_cap = bucket
    bases = list(page_bases) if page_bases is not None else [0] * len(tables)
    per_code = [
        t.padded_device_tables((instr_cap, addr_cap, loops_cap),
                               window_base=bases[i])
        for i, t in enumerate(tables)
    ]
    fills = (O.F_STOP, 0, 0, 0, 0, True, -1, -1, False, False)
    out = []
    for col, fill in enumerate(fills):
        first = per_code[0][col]
        stack = np.full((code_cap,) + first.shape, fill, first.dtype)
        for ci, cols in enumerate(per_code):
            stack[ci] = cols[col]
        out.append(stack)
    pbase = np.zeros(code_cap, np.int32)
    pbase[: len(bases)] = np.asarray(bases, np.int32)
    out.append(pbase)
    return out
