"""Arena decode sort-coercion: word ops over comparison rows.

The device kernel keeps EVM comparison results as 0/1 words; the host
decoder rebuilds comparison rows as Bool terms.  solc-style sequences like
``LT; NOT`` or ``ISZERO; MUL`` therefore hand a Bool to a word operator at
decode time — which crashed the walker ("not a bitvector: eq") and silently
dropped the path (recall loss on the device config only).
"""

from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.support.support_args import args as global_args

# CALLDATALOAD(0); PUSH1 5; LT; NOT; SSTORE(0, .); CALLER; SELFDESTRUCT
# the NOT consumes a symbolic comparison row; SSTORE ships it in an event,
# forcing the walker to decode the bool-typed row as a word operand
CODE = "600035" "6005" "10" "19" "600055" "33" "ff"


def _analyze(frontier: bool):
    reset_callback_modules()
    from mythril_tpu.analysis.module.loader import ModuleLoader

    for m in ModuleLoader().get_detection_modules():
        m.cache.clear()
    old = (global_args.frontier, global_args.frontier_force)
    global_args.frontier, global_args.frontier_force = frontier, frontier
    try:
        sym = SymExecWrapper(
            bytes.fromhex(CODE),
            address=0x0901D12E,
            strategy="bfs",
            transaction_count=1,
            execution_timeout=60,
            modules=["AccidentallyKillable"],
        )
        issues = fire_lasers(sym, white_list=["AccidentallyKillable"])
    finally:
        global_args.frontier, global_args.frontier_force = old
    return sorted((i.swc_id, i.address) for i in issues)


def test_not_over_comparison_row_survives_device_decode():
    host = _analyze(frontier=False)
    dev = _analyze(frontier=True)
    assert host, "selfdestruct not reachable on host"
    assert host == dev, f"device path lost issues: host={host} dev={dev}"
