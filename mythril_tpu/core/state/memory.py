"""Symbolic EVM memory: byte-addressed, keyed by interned index terms.

Reference parity: mythril/laser/ethereum/state/memory.py:28-210.  Hash-consing
makes index canonicalization free (the reference re-simplifies every index);
missing bytes read as zero per EVM semantics.  Symbolic-length copies are
capped (reference APPROX_ITR=100, memory.py:25).
"""

from __future__ import annotations

from typing import Dict, List, Union

from mythril_tpu.smt import BitVec, Concat, Extract, symbol_factory
from mythril_tpu.smt.terms import Term

APPROX_ITR = 100


class Memory:
    def __init__(self):
        # raw index term -> byte BitVec
        self._memory: Dict[Term, BitVec] = {}

    def __copy__(self) -> "Memory":
        out = Memory.__new__(Memory)
        out._memory = dict(self._memory)
        return out

    copy = __copy__

    def _key(self, index: Union[int, BitVec]) -> Term:
        if isinstance(index, int):
            index = symbol_factory.BitVecVal(index, 256)
        return index.raw

    def __getitem__(self, index) -> BitVec:
        if isinstance(index, slice):
            start, stop = index.start, index.stop
            return [self.get_byte(start + i) for i in range(stop - start)]
        return self.get_byte(index)

    def __setitem__(self, index, value) -> None:
        if isinstance(index, slice):
            start = index.start
            for i, b in enumerate(value):
                self.set_byte(start + i, b)
            return
        self.set_byte(index, value)

    def get_byte(self, index) -> BitVec:
        key = self._key(index)
        v = self._memory.get(key)
        return v if v is not None else symbol_factory.BitVecVal(0, 8)

    def set_byte(self, index, value) -> None:
        if isinstance(value, int):
            value = symbol_factory.BitVecVal(value, 8)
        if value.size() != 8:
            value = Extract(7, 0, value)
        self._memory[self._key(index)] = value

    def __len__(self) -> int:
        return len(self._memory)

    def concrete_addresses(self):
        """Sorted concrete byte addresses of every written byte, or None if
        any index is symbolic (used by the frontier's mid-frame encoder to
        decide whether this memory can be packed into device entries)."""
        out = []
        for key in self._memory:
            if key.is_const:
                out.append(key.value)
            else:
                return None
        return sorted(out)

    def get_word_at(self, index) -> BitVec:
        """Big-endian 32-byte word at byte offset ``index``."""
        if isinstance(index, int):
            index = symbol_factory.BitVecVal(index, 256)
        return Concat(*[self.get_byte(index + i) for i in range(32)])

    def write_word_at(self, index, value) -> None:
        if isinstance(index, int):
            index = symbol_factory.BitVecVal(index, 256)
        if isinstance(value, int):
            value = symbol_factory.BitVecVal(value, 256)
        if isinstance(value, bool):
            value = symbol_factory.BitVecVal(1 if value else 0, 256)
        if hasattr(value, "is_true"):  # Bool -> 0/1 word
            from mythril_tpu.smt import If

            value = If(value, symbol_factory.BitVecVal(1, 256), symbol_factory.BitVecVal(0, 256))
        assert value.size() == 256
        for i in range(32):
            self.set_byte(index + i, Extract(255 - 8 * i, 248 - 8 * i, value))

    def write_bytes(self, index, data) -> None:
        """Write a run of bytes (ints or 8-bit BitVecs) starting at index."""
        if isinstance(index, int):
            index = symbol_factory.BitVecVal(index, 256)
        for i, b in enumerate(data):
            self.set_byte(index + i, b)

    def read_bytes(self, index, length: int) -> List[BitVec]:
        if isinstance(index, int):
            index = symbol_factory.BitVecVal(index, 256)
        return [self.get_byte(index + i) for i in range(length)]
