"""Drift doctor: ranked attribution of perf movement between two runs.

Diagnosing a bench regression used to be a ritual: open two
``BENCH_*.json`` files side by side and eyeball which of the ~40 numbers
per workload moved.  This module makes attribution a tool.  It is a
*pure differ* — no registry access, no jax — over two inputs:

* two bench artifacts (``myth drift A.json B.json``), in any of the
  formats bench.py itself accepts (snapshot, driver wrapper, truncated
  tail); or
* two adjacent windows of a metrics history ring
  (``myth drift --history DIR``), via ``HistoryReader`` samples.

For every workload it extracts a fixed set of metrics (speedup, rates,
TTFE, harvest share and per-phase split, compile wall and cache
hit/miss, prefilter kill rate, coverage, spread noise), computes the
relative movement of each, weights it by how much that metric is known
to matter, and ranks the result.  The top of the ranking *names the
most-moved phase/counter* — which is exactly what ``bench.py``'s
``regression_gate`` prints on failure, so a breached threshold arrives
with its probable cause attached.

Torn inputs are data, not errors: workloads present on only one side
are reported (``only_in_prior`` / ``only_in_current``), metrics missing
from a row are skipped, non-numeric values are skipped.  The differ
never raises on artifact shape.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "attribute",
    "diff_history_windows",
    "diff_tables",
    "format_drift",
    "load_bench_table",
]

# movement below this fraction is noise, not a finding
MIN_REL = 0.02
# relative movement is clipped here so a 0 -> something transition cannot
# drown every real finding (appears as ">=300%")
REL_CAP = 3.0
_EPS = 1e-9


def _get(row: Dict[str, Any], path: Sequence[str]) -> Optional[float]:
    cur: Any = row
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return None
        cur = cur[key]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def _spread_width(row: Dict[str, Any]) -> Optional[float]:
    """Production spread width as % of the production rate (noise)."""
    spread = row.get("spread")
    mid = row.get("production")
    if (not isinstance(spread, dict)
            or not isinstance(mid, (int, float)) or not mid):
        return None
    lohi = spread.get("production")
    if (not isinstance(lohi, (list, tuple)) or len(lohi) != 2
            or not all(isinstance(v, (int, float)) for v in lohi)):
        return None
    return 100.0 * (float(lohi[1]) - float(lohi[0])) / abs(float(mid))


# (metric label, extractor, higher_is_better, weight).  Weights encode
# how directly each metric explains a speedup movement: the headline
# ratio and the phase walls that compose it rank above ambient
# counters.  higher_is_better=None means movement is reported neutrally.
_SPECS: List[Tuple[str, Callable[[Dict[str, Any]], Optional[float]],
                   Optional[bool], float]] = [
    ("speedup", lambda r: _get(r, ("speedup",)), True, 3.0),
    ("production_rate", lambda r: _get(r, ("production",)), True, 2.0),
    ("baseline_rate", lambda r: _get(r, ("baseline",)), True, 1.0),
    ("ttfe_s.production", lambda r: _get(r, ("ttfe_s", "production")),
     False, 2.5),
    ("ttfe_s.baseline", lambda r: _get(r, ("ttfe_s", "baseline")),
     False, 1.0),
    ("harvest_share_pct", lambda r: _get(r, ("harvest_share_pct",)),
     False, 1.5),
    ("harvest_phase_s.ingest",
     lambda r: _get(r, ("harvest_phase_s", "ingest")), False, 2.0),
    ("harvest_phase_s.solver",
     lambda r: _get(r, ("harvest_phase_s", "solver")), False, 2.0),
    ("harvest_phase_s.replay",
     lambda r: _get(r, ("harvest_phase_s", "replay")), False, 2.0),
    ("harvest_phase_s.commit",
     lambda r: _get(r, ("harvest_phase_s", "commit")), False, 2.0),
    ("compile_s.production", lambda r: _get(r, ("compile_s", "production")),
     False, 2.0),
    ("device.compile_wall_s",
     lambda r: _get(r, ("device", "compile_wall_s")), False, 2.0),
    ("device.recompiles", lambda r: _get(r, ("device", "recompiles")),
     False, 1.5),
    ("compilecache.production.misses",
     lambda r: _get(r, ("compilecache", "production", "misses")),
     False, 1.0),
    ("prefilter.kill_rate",
     lambda r: _get(r, ("prefilter", "kill_rate")), True, 1.5),
    ("devsolver.decide_rate",
     lambda r: _get(r, ("devsolver", "decide_rate")), True, 1.5),
    ("devsolver.decided",
     lambda r: _get(r, ("devsolver", "decided")), True, 1.0),
    ("exploration.coverage_pct",
     lambda r: _get(r, ("exploration", "coverage_pct")), True, 1.5),
    ("exploration.coverage_pct_reachable",
     lambda r: _get(r, ("exploration", "coverage_pct_reachable")),
     True, 1.5),
    # the reachable-edge denominator itself: movement means the corpus
    # or the static oracle changed, not that the run got better/worse
    ("staticpass.reachable_edge_pct",
     lambda r: _get(r, ("staticpass", "reachable_edge_pct")), None, 1.0),
    ("device_residency_pct", lambda r: _get(r, ("device_residency_pct",)),
     True, 1.0),
    # large-code frontier pad economics: pad waste is padded cells the
    # device computes but the corpus never uses — lower is strictly
    # better.  Paging pressure is reported neutrally (faults trade
    # against pad waste; neither direction alone means regression)
    ("frontier.pad_waste_pct",
     lambda r: _get(r, ("frontier", "pad_waste_pct")), False, 2.0),
    ("frontier.bucket_classes",
     lambda r: _get(r, ("frontier", "bucket_classes")), None, 1.0),
    ("frontier.page_faults",
     lambda r: _get(r, ("frontier", "page_faults")), None, 1.0),
    ("frontier.page_repacks",
     lambda r: _get(r, ("frontier", "page_repacks")), None, 1.0),
    ("frontier.page_resident_pct",
     lambda r: _get(r, ("frontier", "page_resident_pct")), True, 1.0),
    # adaptive steering: fewer dispatched segments at equal issue sets is
    # the controller doing its job; resteer/requeue volume is reported
    # neutrally (more steering is not inherently better or worse)
    ("segments_dispatched", lambda r: _get(r, ("segments_dispatched",)),
     False, 1.5),
    ("adaptive.resteered_slots",
     lambda r: _get(r, ("adaptive", "resteered_slots")), None, 1.0),
    ("adaptive.requeued_paths",
     lambda r: _get(r, ("adaptive", "requeued_paths")), None, 1.0),
    ("adaptive.flip_hit_rate",
     lambda r: _get(r, ("adaptive", "flip_hit_rate")), True, 1.0),
    ("spread.production.width_pct", _spread_width, False, 1.0),
]


def _finding(workload: str, metric: str, prior: float, current: float,
             higher_is_better: Optional[bool],
             weight: float) -> Optional[Dict[str, Any]]:
    delta = current - prior
    rel = delta / max(abs(prior), _EPS)
    rel = max(-REL_CAP, min(REL_CAP, rel))
    if abs(rel) < MIN_REL:
        return None
    if higher_is_better is None:
        direction = "moved"
    elif (rel > 0) == higher_is_better:
        direction = "improved"
    else:
        direction = "regressed"
    score = weight * abs(rel)
    if direction == "regressed":
        # a regression outranks an equally-sized improvement: the tool's
        # job is to answer "what went wrong", not "what happened"
        score *= 1.5
    return {
        "workload": workload,
        "metric": metric,
        "prior": round(prior, 6),
        "current": round(current, 6),
        "delta": round(delta, 6),
        "rel_pct": round(100.0 * rel, 1),
        "direction": direction,
        "score": round(score, 4),
    }


def diff_tables(prior: Dict[str, Any], current: Dict[str, Any],
                prior_name: str = "prior",
                current_name: str = "current") -> Dict[str, Any]:
    """Rank per-workload metric movement between two workload tables.

    ``prior``/``current`` are bench ``workloads`` tables (name -> row).
    Pure function; tolerant of torn rows and missing workloads.
    """
    prior = prior if isinstance(prior, dict) else {}
    current = current if isinstance(current, dict) else {}
    shared = [w for w in current if w in prior
              and isinstance(prior[w], dict) and isinstance(current[w], dict)]
    findings: List[Dict[str, Any]] = []
    for workload in shared:
        p_row, c_row = prior[workload], current[workload]
        for metric, extract, better, weight in _SPECS:
            p_v, c_v = extract(p_row), extract(c_row)
            if p_v is None or c_v is None:
                continue
            f = _finding(workload, metric, p_v, c_v, better, weight)
            if f is not None:
                findings.append(f)
    findings.sort(key=lambda f: -f["score"])
    report = {
        "mode": "bench",
        "prior": prior_name,
        "current": current_name,
        "workloads_compared": sorted(shared),
        "only_in_prior": sorted(w for w in prior if w not in current),
        "only_in_current": sorted(w for w in current if w not in prior),
        "ranked": findings,
    }
    report["headline"] = attribute(report)
    return report


def attribute(report: Dict[str, Any],
              workload: Optional[str] = None) -> str:
    """One line naming the most-moved metric (optionally per workload).

    This is what the regression gate prints next to a breached
    threshold, so ``workload`` lets the gate ask about the violator.
    """
    ranked = report.get("ranked") or []
    if workload is not None:
        ranked = [f for f in ranked if f.get("workload") == workload]
    if not ranked:
        return "drift: no metric moved beyond noise"
    top = ranked[0]
    return (
        "drift: most-moved {w}: {m} {p:g} -> {c:g} ({r:+.1f}%, {d})".format(
            w=top.get("workload", "?"), m=top["metric"], p=top["prior"],
            c=top["current"], r=top["rel_pct"], d=top["direction"],
        )
    )


def format_drift(report: Dict[str, Any], limit: int = 15) -> str:
    """Render a ranked attribution report for terminals."""
    lines = [
        "drift report  {} -> {}".format(report.get("prior", "?"),
                                        report.get("current", "?")),
    ]
    compared = report.get("workloads_compared")
    if compared is not None:
        lines.append("compared workloads: "
                     + (", ".join(compared) or "(none)"))
    for side, key in (("prior", "only_in_prior"),
                      ("current", "only_in_current")):
        extra = report.get(key)
        if extra:
            lines.append(f"only in {side}: " + ", ".join(extra))
    ranked = report.get("ranked") or []
    if not ranked:
        lines.append("no metric moved beyond noise")
        return "\n".join(lines)
    lines.append("")
    lines.append(f"{'#':>3} {'workload':<18} {'metric':<30}"
                 f"{'prior':>12} {'current':>12} {'move':>9}  verdict")
    for i, f in enumerate(ranked[:limit], 1):
        lines.append(
            f"{i:>3} {f.get('workload', '?'):<18} {f['metric']:<30}"
            f"{f['prior']:>12g} {f['current']:>12g}"
            f"{f['rel_pct']:>+8.1f}%  {f['direction'].upper()}"
        )
    if len(ranked) > limit:
        lines.append(f"    ... and {len(ranked) - limit} more")
    lines.append("")
    lines.append(report.get("headline") or attribute(report))
    return "\n".join(lines)


# -- history-window mode ---------------------------------------------------

# direction hints for live service/frontier series; anything unlisted is
# reported neutrally ("moved")
_HISTORY_LOWER_IS_BETTER = (
    "service.request_errors", "service.shed_total",
    "service.quota_rejections", "heartbeat.device_recompiles",
    "heartbeat.device_shape_churn", "heartbeat.device_compile_s",
    "slo.breaches_total",
)


def diff_history_windows(samples: Sequence[Tuple[float, Dict[str, Any]]],
                         window_s: float,
                         bounds: Optional[Dict[str, Tuple[float, ...]]]
                         = None) -> Dict[str, Any]:
    """Compare the last ``window_s`` of a history ring to the window
    before it.

    ``samples`` is a time-ordered ``[(t, values)]`` sequence in the
    history wire format (counters as numbers, histograms as
    ``{"c","s","mn","mx","bc"}`` dicts, label maps as flat dicts).
    Counters and histogram sums compare as per-window deltas (rates);
    histogram windows additionally compare the window p50 when bucket
    ``bounds`` are known.  Pure over the sample list.
    """
    from mythril_tpu.observability.history import (
        counter_window,
        window_percentile,
    )

    samples = list(samples)
    report_base = {
        "mode": "history",
        "prior": f"window [-{2 * window_s:g}s, -{window_s:g}s)",
        "current": f"window [-{window_s:g}s, now]",
        "ranked": [],
    }
    if not samples:
        report_base["headline"] = "drift: history is empty"
        return report_base
    t_end = samples[-1][0]
    a0, a1 = t_end - 2 * window_s, t_end - window_s
    b0, b1 = t_end - window_s, t_end

    names: Dict[str, Any] = {}
    for _, vals in samples:
        for k, v in vals.items():
            names.setdefault(k, v)

    findings: List[Dict[str, Any]] = []

    def _rank(metric: str, prior_v: float, current_v: float) -> None:
        better = (False if metric.split(".p")[0]
                  in _HISTORY_LOWER_IS_BETTER
                  or metric.rsplit(".", 1)[0] in _HISTORY_LOWER_IS_BETTER
                  else None)
        f = _finding("(window)", metric, prior_v, current_v, better, 1.0)
        if f is not None:
            findings.append(f)

    for name, example in sorted(names.items()):
        if isinstance(example, dict) and "bc" in example:
            # histogram: compare per-window observation rate and p50
            da = _hist_window_sum(samples, name, a0, a1)
            db = _hist_window_sum(samples, name, b0, b1)
            if da is not None and db is not None:
                _rank(name + ".rate_hz", da[0] / max(window_s, _EPS),
                      db[0] / max(window_s, _EPS))
                if da[0] and db[0]:
                    _rank(name + ".avg_s", da[1] / da[0], db[1] / db[0])
            if bounds and name in bounds:
                pa, _na = window_percentile(samples, name, 0.5, a0, a1,
                                            bounds)
                pb, _nb = window_percentile(samples, name, 0.5, b0, b1,
                                            bounds)
                if pa is not None and pb is not None:
                    _rank(name + ".p50", pa, pb)
        elif isinstance(example, dict):
            # label map: total per-window delta
            da = _labeled_window(samples, name, a0, a1)
            db = _labeled_window(samples, name, b0, b1)
            _rank(name + ".total", da, db)
        elif isinstance(example, (int, float)):
            _rank(name, counter_window(samples, name, a0, a1),
                  counter_window(samples, name, b0, b1))

    findings.sort(key=lambda f: -f["score"])
    report = dict(report_base)
    report["ranked"] = findings
    report["headline"] = attribute(report)
    return report


def _hist_window_sum(samples, name: str, t0: float,
                     t1: float) -> Optional[Tuple[float, float]]:
    """(count delta, sum delta) of histogram ``name`` over ``(t0, t1]``."""
    s0 = s1 = None
    for t, vals in samples:
        if t > t1:
            break
        if t <= t0:
            s0 = vals
        s1 = vals
    end = (s1 or {}).get(name)
    if not isinstance(end, dict) or "c" not in end:
        return None
    base = (s0 or {}).get(name)
    c0 = base.get("c", 0) if isinstance(base, dict) else 0
    sum0 = base.get("s", 0.0) if isinstance(base, dict) else 0.0
    c1, sum1 = end.get("c", 0), end.get("s", 0.0)
    if not isinstance(c1, (int, float)) or c1 < c0:
        # restart seam: take everything since the restart
        return float(c1 or 0), float(sum1 or 0.0)
    return float(c1 - c0), float((sum1 or 0.0) - (sum0 or 0.0))


def _labeled_window(samples, name: str, t0: float, t1: float) -> float:
    s0 = s1 = None
    for t, vals in samples:
        if t > t1:
            break
        if t <= t0:
            s0 = vals
        s1 = vals
    end = (s1 or {}).get(name)
    base = (s0 or {}).get(name)
    total1 = (sum(v for v in end.values() if isinstance(v, (int, float)))
              if isinstance(end, dict) else 0.0)
    total0 = (sum(v for v in base.values() if isinstance(v, (int, float)))
              if isinstance(base, dict) else 0.0)
    return float(total1 if total1 < total0 else total1 - total0)


# -- artifact loading ------------------------------------------------------


def load_bench_table(path: str) -> Dict[str, Any]:
    """Load a bench artifact's workload table (mirrors bench.py's
    loader contract: snapshot, driver wrapper, or raw stdout tail —
    the last parseable snapshot line wins).  Returns ``{}`` when no
    table can be recovered (torn artifacts are tolerated, not fatal).
    """
    try:
        raw = Path(path).read_text()
    except OSError:
        return {}
    try:
        doc = json.loads(raw)
    except ValueError:
        doc = None
    text = raw
    if isinstance(doc, dict):
        if isinstance(doc.get("workloads"), dict):
            return doc["workloads"]
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and isinstance(parsed.get("workloads"),
                                                   dict):
            return parsed["workloads"]
        text = doc.get("tail") or ""
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and isinstance(obj.get("workloads"), dict):
            return obj["workloads"]
    return {}
