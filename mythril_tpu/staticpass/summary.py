"""StaticSummary: one immutable result object per analyzed bytecode.

``summarize`` runs the static passes (CFG recovery, interprocedural
value-set refinement, abstract stack height, taint reachability,
function recovery) once over a decoded instruction stream;
``summary_for_code`` adds a process-wide cache keyed by bytecode hash so
the frontier engine, the detector gate and the CLI report all share one
computation per contract.

The interprocedural layer (:mod:`interproc`/:mod:`functions`) is
best-effort on top of the base pass: refinement that exhausts its
budget, trips the reachability-subset invariant, or throws falls back
to the base CFG (counted under ``staticpass.interproc_fallback``) —
the summary is then exactly what the intra-procedural pass produced.
"""

from __future__ import annotations

import hashlib
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from mythril_tpu.staticpass.cfg import E_FALL, StaticCFG
from mythril_tpu.staticpass.errors import StaticPassError, invariant
from mythril_tpu.staticpass.stackheight import underflow_points
from mythril_tpu.staticpass.taintflow import may_reach

log = logging.getLogger(__name__)

_CACHE: Dict[tuple, "StaticSummary"] = {}
_CACHE_CAP = 512


@dataclass(frozen=True)
class StaticSummary:
    n_instructions: int
    code_size: int
    n_blocks: int
    n_reachable_blocks: int
    block_starts: np.ndarray  # instr idx per block
    block_addrs: np.ndarray  # byte addr per block
    edges: List[Tuple[int, int, str]]  # (from_block, to_block, kind)
    instr_reachable: np.ndarray  # bool [n]
    reachable_opcodes: frozenset
    static_target: np.ndarray  # int32 [n]: resolved jump dest instr or -1
    n_resolved_jumps: int
    underflow_blocks: int
    unreachable_spans: List[Tuple[int, int]]  # [start_addr, end_addr) bytes
    unreachable_bytes: int
    may_reach: Dict[int, frozenset] = field(default_factory=dict)
    escalated_bits: frozenset = frozenset()
    is_creation: bool = False
    wall_s: float = 0.0
    # interprocedural layer (all best-effort; defaults = "layer absent")
    interproc_ok: bool = False
    edge_taken_live: Optional[np.ndarray] = None  # bool [n] at JUMPIs
    edge_fall_live: Optional[np.ndarray] = None  # bool [n] at JUMPIs
    n_jumpis: int = 0
    n_edges_total: int = 0  # 2 * |JUMPI|
    n_edges_live: int = 0
    reachable_edge_pct: float = 100.0
    function_map: Optional[object] = None  # functions.FunctionMap
    interesting_points: Tuple[dict, ...] = ()

    def taint_reach(self, bit: int) -> frozenset:
        return self.may_reach.get(bit, frozenset())


def _edge_liveness(flow, block_reach, halting):
    """Per-JUMPI taken/fall edge liveness derived from the (refined)
    successor kinds, masked by block reachability: an edge is live iff
    its JUMPI sits in a reachable non-halting block and the flow kept
    an edge of that kind."""
    t = flow.tables
    n = t.n
    taken = np.zeros(n, bool)
    fall = np.zeros(n, bool)
    for b in range(flow.n_blocks):
        last = int(flow.block_end[b]) - 1
        if not t.is_jumpi[last]:
            continue
        if not block_reach[b] or halting[b]:
            continue
        for kind in flow.succ_kind[b]:
            if kind == E_FALL:
                fall[last] = True
            else:
                taken[last] = True
    return taken, fall


def summarize(instruction_list: List, code_size: int = 0,
              is_creation: bool = False) -> StaticSummary:
    """Run the full static pass over one decoded instruction stream."""
    from mythril_tpu.frontier import taint
    from mythril_tpu.staticpass.tables import InstrTables
    from mythril_tpu.support.support_args import args

    t0 = time.perf_counter()
    tables = InstrTables(instruction_list)
    cfg = StaticCFG(tables)

    # interprocedural value-set refinement (best-effort, only removes
    # edges; any failure keeps the sound base CFG)
    refined = None
    if getattr(args, "staticpass_interproc", True):
        from mythril_tpu.staticpass.interproc import refine

        try:
            refined = refine(cfg)
            if refined is None:
                _count("staticpass.interproc_fallback")
            else:
                # soundness net: refinement must not reach blocks the
                # base over-approximation proves unreachable
                base_reach = cfg.reachable_blocks()
                ref_reach = refined.reachable_blocks()
                invariant(
                    not bool((ref_reach & ~base_reach).any()),
                    "refined reachability exceeds base over-approximation",
                )
        except StaticPassError as e:
            log.warning("interprocedural refinement dropped: %s", e)
            _count("staticpass.interproc_fallback")
            refined = None
        except Exception as e:
            log.warning("interprocedural refinement failed: %s", e)
            _count("staticpass.interproc_fallback")
            refined = None
    flow = refined if refined is not None else cfg

    under = underflow_points(flow)
    halting = under >= 0
    block_reach = flow.reachable_blocks(halting=halting)

    n = tables.n
    instr_reach = np.zeros(n, bool)
    for b in np.flatnonzero(block_reach):
        s, e = int(flow.block_start[b]), int(flow.block_end[b])
        if halting[b]:
            # the underflowing instruction itself executes (and halts);
            # everything after it in the block is dead
            instr_reach[s: int(under[b]) + 1] = True
        else:
            instr_reach[s:e] = True

    spans: List[Tuple[int, int]] = []
    unreachable_bytes = 0
    dead = np.flatnonzero(~instr_reach)
    if len(dead):
        unreachable_bytes = int(tables.width[dead].sum())
        run_start = dead[0]
        prev = dead[0]
        for i in dead[1:]:
            if i != prev + 1:
                spans.append(_span(tables, run_start, prev))
                run_start = i
            prev = i
        spans.append(_span(tables, run_start, prev))

    reach_ops = frozenset(tables.names[i] for i in np.flatnonzero(instr_reach))
    flows, escalated = may_reach(
        flow, block_reach, instr_reach, halting,
        taint.SOURCE_OPCODES, is_creation=is_creation,
    )
    # resolved targets on unreachable jumps are meaningless downstream
    static_target = np.where(instr_reach, flow.static_target, -1).astype(np.int32)

    # reachable-edge oracle: per-JUMPI edge liveness + the corrected
    # coverage denominator
    taken_live, fall_live = _edge_liveness(flow, block_reach, halting)
    n_jumpis = int(tables.is_jumpi.sum())
    n_edges_total = 2 * n_jumpis
    n_edges_live = int(taken_live.sum()) + int(fall_live.sum())
    invariant(
        n_edges_live <= n_edges_total,
        "live edge count exceeds the total edge count",
    )
    reachable_edge_pct = (
        100.0 * n_edges_live / n_edges_total if n_edges_total else 100.0
    )

    # function recovery + per-function summaries (advisory part of the
    # interprocedural layer — gated with it)
    function_map = None
    points: Tuple[dict, ...] = ()
    if getattr(args, "staticpass_interproc", True):
        try:
            from mythril_tpu.staticpass.functions import (
                interesting_points,
                recover_functions,
            )

            function_map = recover_functions(flow, instr_reach)
            points = tuple(interesting_points(function_map))
        except Exception as e:
            log.warning(
                "function recovery failed (summaries degraded): %s", e
            )
            _count("staticpass.function_recovery_failed")

    return StaticSummary(
        n_instructions=n,
        code_size=code_size or (int(tables.addr[-1] + tables.width[-1]) if n else 0),
        n_blocks=flow.n_blocks,
        n_reachable_blocks=int(block_reach.sum()),
        block_starts=flow.block_start,
        block_addrs=tables.addr[flow.block_start] if flow.n_blocks else np.zeros(0, np.int32),
        edges=flow.edge_list(),
        instr_reachable=instr_reach,
        reachable_opcodes=reach_ops,
        static_target=static_target,
        n_resolved_jumps=flow.n_resolved,
        underflow_blocks=int((halting & block_reach).sum()),
        unreachable_spans=spans,
        unreachable_bytes=unreachable_bytes,
        may_reach=flows,
        escalated_bits=escalated,
        is_creation=is_creation,
        wall_s=time.perf_counter() - t0,
        interproc_ok=refined is not None,
        edge_taken_live=taken_live,
        edge_fall_live=fall_live,
        n_jumpis=n_jumpis,
        n_edges_total=n_edges_total,
        n_edges_live=n_edges_live,
        reachable_edge_pct=reachable_edge_pct,
        function_map=function_map,
        interesting_points=points,
    )


def _span(tables, first: int, last: int) -> Tuple[int, int]:
    return (int(tables.addr[first]),
            int(tables.addr[last] + tables.width[last]))


def summary_for_code(code, is_creation: bool = False) -> Optional[StaticSummary]:
    """Cached summary for a Disassembly-like object (``.bytecode`` bytes +
    ``.instruction_list``).  Returns None when the pass is disabled or
    fails — every consumer treats None as "no static information"."""
    from mythril_tpu.support.support_args import args

    if not getattr(args, "staticpass", True):
        return None
    try:
        bytecode = getattr(code, "bytecode", None) or b""
        if isinstance(bytecode, str):
            bytecode = bytes.fromhex(
                bytecode[2:] if bytecode.startswith("0x") else bytecode
            )
        instruction_list = code.instruction_list
        key = (
            hashlib.sha1(bytecode).hexdigest(),
            len(instruction_list),
            is_creation,
            bool(getattr(args, "staticpass_interproc", True)),
        )
        hit = _CACHE.get(key)
        if hit is not None:
            _count("staticpass.cache_hits")
            return hit
        _count("staticpass.cache_misses")
        summary = summarize(
            instruction_list, code_size=len(bytecode), is_creation=is_creation
        )
        if len(_CACHE) >= _CACHE_CAP:
            _CACHE.clear()
        _CACHE[key] = summary
        return summary
    except Exception as e:  # over-approximation escape hatch: never fatal
        log.warning("static pass failed (analysis continues without it): %s", e)
        return None


def publish_reachability(code, summary: Optional[StaticSummary]) -> None:
    """Register a summary's reachability masks with the exploration
    ledger, keyed by the same keccak code hash the engines use, so
    coverage can be reported over the statically reachable denominator
    (`coverage_pct_reachable`) next to the raw one."""
    if summary is None or summary.edge_taken_live is None:
        return
    try:
        from mythril_tpu.observability.exploration import get_exploration_ledger
        from mythril_tpu.support.support_utils import get_code_hash

        bytecode = getattr(code, "bytecode", None) or b""
        if isinstance(bytecode, (bytes, bytearray)):
            hex_code = bytes(bytecode).hex()
        else:
            hex_code = bytecode
        get_exploration_ledger().register_static(
            get_code_hash(hex_code),
            summary.instr_reachable,
            summary.edge_taken_live,
            summary.edge_fall_live,
        )
    except Exception as e:  # observe-only plumbing: never fatal
        log.debug("static reachability not published: %s", e)


def _count(name: str, n: int = 1) -> None:
    from mythril_tpu.observability import get_registry

    get_registry().counter(name).inc(n)


# aggregate live/total edge counts across every recorded summary, so the
# staticpass.reachable_edge_pct gauge reflects the whole process
_EDGE_TOTALS = {"live": 0, "total": 0}


def record_summary_metrics(summary: StaticSummary) -> None:
    """Publish one summary's counters (report meta / --metrics-out)."""
    _count("staticpass.contracts")
    _count("staticpass.blocks", summary.n_blocks)
    _count("staticpass.unreachable_bytes", summary.unreachable_bytes)
    _count("staticpass.jumps_resolved", summary.n_resolved_jumps)
    _count("staticpass.underflow_blocks", summary.underflow_blocks)
    if summary.interproc_ok:
        _count("staticpass.interproc_refined")
    if summary.function_map is not None:
        _count("staticpass.functions_recovered",
               len(summary.function_map.functions))
    _count("staticpass.edges_live", summary.n_edges_live)
    _count("staticpass.edges_total", summary.n_edges_total)
    _count("staticpass.interesting_points", len(summary.interesting_points))
    from mythril_tpu.observability import get_registry

    get_registry().counter("staticpass.wall_time_s").inc(round(summary.wall_s, 6))
    _EDGE_TOTALS["live"] += summary.n_edges_live
    _EDGE_TOTALS["total"] += summary.n_edges_total
    if _EDGE_TOTALS["total"]:
        get_registry().gauge("staticpass.reachable_edge_pct").set(
            round(100.0 * _EDGE_TOTALS["live"] / _EDGE_TOTALS["total"], 3)
        )


def clear_cache() -> None:
    _CACHE.clear()
    _EDGE_TOTALS["live"] = 0
    _EDGE_TOTALS["total"] = 0
