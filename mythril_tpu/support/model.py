"""get_model: the one model-query entry point used across the framework.

Reference parity: mythril/support/model.py:15-63 — memoized over the constraint
tuple, applies the solver timeout clamped by remaining execution time, raises
UnsatError on unsat/unknown.  Here the query routes to the probe/CDCL stack
(mythril_tpu/smt/solver.py) instead of a z3 Optimize instance.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

from mythril_tpu.exceptions import UnsatError
from mythril_tpu.smt.solver import Model, Optimize, ProbeConfig, SAT, UNSAT
from mythril_tpu.support.support_args import args
from mythril_tpu.support.time_handler import time_handler


def get_model(
    constraints,
    minimize=(),
    maximize=(),
    enforce_execution_time: bool = True,
    solver_timeout: Optional[int] = None,
    session=None,
    session_enable: Sequence[int] = (),
) -> Model:
    """Solve ``constraints``; return a Model or raise UnsatError.

    ``session``/``session_enable``: an externally-owned live CDCL session
    (the tx-end issue gate's) that has already blasted this formula family —
    the Optimize answers its initial solve and every bound query under
    assumptions against it instead of re-blasting (caller keeps ownership)."""
    timeout = solver_timeout if solver_timeout is not None else args.solver_timeout
    if enforce_execution_time:
        timeout = min(timeout, int(max(time_handler.time_remaining(), 0) * 1000) // 2 + 1)
    if timeout <= 0:
        raise UnsatError("solver budget exhausted")

    raws = tuple(c.raw if hasattr(c, "raw") else c for c in constraints)
    min_raws = tuple(m.raw if hasattr(m, "raw") else m for m in minimize)
    max_raws = tuple(m.raw if hasattr(m, "raw") else m for m in maximize)
    # the cache key must NOT include the timeout: it is derived from the
    # REMAINING execution time, so it differs on every call and would
    # fragment the cache into all-misses.  A SAT result is valid under any
    # budget; UNSAT/UNKNOWN raise and are never cached.
    key = (raws, min_raws, max_raws)
    hit = _model_memo.get(key)
    if hit is not None:
        return hit
    model, proven = _get_model_cached(
        raws, min_raws, max_raws, timeout, session, session_enable
    )
    if proven:
        # only PROVEN-optimal (or objective-free) models memoize: a
        # budget-truncated refinement must re-solve under a later, larger
        # budget instead of serving its unrefined model forever
        if len(_model_memo) >= 2**18:
            _model_memo.pop(next(iter(_model_memo)))  # FIFO, not flush
        _model_memo[key] = model
    return model


_model_memo: dict = {}


def _get_model_cached(
    raws: tuple,
    min_raws: tuple,
    max_raws: tuple,
    timeout: int,
    session=None,
    session_enable: Sequence[int] = (),
) -> Tuple[Model, bool]:
    # (kept as a separate function so the memo layer above stays readable;
    # ``cache_clear`` mirrors the old lru_cache surface for bench/tests)
    opt = Optimize(
        ProbeConfig(
            max_rounds=args.probe_rounds,
            candidates_per_round=args.probe_candidates,
            timeout_ms=timeout,
        ),
        session=session,
        session_enable=session_enable,
    )
    opt.add(*raws)
    for m in min_raws:
        opt.minimize(m)
    for m in max_raws:
        opt.maximize(m)
    if args.solver_log:
        _dump_query(raws, args.solver_log)
    status = opt.check()
    if status != SAT:
        raise UnsatError(f"no model found ({status})")
    return opt.model(), opt.proven_optimal


# compatibility with the old lru_cache surface (bench/_clear_caches and the
# recall-differential suite call _get_model_cached.cache_clear())
_get_model_cached.cache_clear = _model_memo.clear

_dump_counter = [0]


def _dump_query(raws, directory: str) -> None:
    """Dump the query term dump (the .ir analogue of --solver-log .smt2 files)."""
    os.makedirs(directory, exist_ok=True)
    _dump_counter[0] += 1
    path = os.path.join(directory, f"query_{_dump_counter[0]:06d}.ir")
    with open(path, "w") as f:
        for r in raws:
            f.write(repr(r) + "\n")
