"""ArbitraryDelegateCall: DELEGATECALL into an attacker-chosen contract (SWC-112).

Reference parity: mythril/analysis/module/modules/delegatecall.py:1-99.
"""

from __future__ import annotations

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import DELEGATECALL_TO_UNTRUSTED_CONTRACT
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core.transaction.symbolic import ACTORS

DESCRIPTION = "Check for invocations of delegatecall to a user-supplied address."


class ArbitraryDelegateCall(DetectionModule):
    name = "Delegatecall to a user-specified address"
    swc_id = DELEGATECALL_TO_UNTRUSTED_CONTRACT
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["DELEGATECALL"]
    # staticpass: nothing to report without a DELEGATECALL
    static_required_ops = frozenset({"DELEGATECALL"})

    def _execute(self, state: GlobalState) -> None:
        if self._cache_key(state) in self.cache:
            return None
        self._analyze_state(state)
        return None

    def _analyze_state(self, state: GlobalState) -> None:
        target = state.mstate.stack[-2]
        if target.value is not None:
            return  # fixed library target: fine
        constraints = [target == ACTORS.attacker]
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.node.function_name if state.node else "unknown",
            address=state.get_current_instruction()["address"],
            swc_id=DELEGATECALL_TO_UNTRUSTED_CONTRACT,
            title="Delegatecall to user-supplied address",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head="The contract delegates execution to another contract with a user-supplied address.",
            description_tail=(
                "The smart contract delegates execution to a user-supplied "
                "address. This could allow an attacker to execute arbitrary code "
                "in the context of this contract account and manipulate the state "
                "of the contract account or execute actions on its behalf."
            ),
            detector=self,
            constraints=constraints,
        )
        get_potential_issues_annotation(state).potential_issues.append(potential_issue)


detector = ArbitraryDelegateCall
