"""End-to-end query cache: a warm re-analysis against a shared disk store
must serve from the cache (nonzero hit-rate) and produce the identical
issue set — the acceptance criterion for cached-verdict soundness."""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parents[2]))

import bench  # noqa: E402
from mythril_tpu.observability import get_registry, observability_meta  # noqa: E402
from mythril_tpu.querycache import configure, get_query_cache, \
    reset_query_cache  # noqa: E402


def _issue_keys(issues):
    return sorted((i.swc_id, i.address) for i in issues)


def test_warm_run_hits_and_matches_cold_issue_set(tmp_path):
    try:
        configure(enabled=True, cache_dir=str(tmp_path))

        get_registry().reset(prefix="querycache.")
        _, cold_issues, _ = bench.run_analysis("host")
        bench.check_recall(cold_issues)
        cold_stats = get_query_cache().stats()
        assert cold_stats["stores"] > 0, "cold run recorded nothing"

        # run_analysis -> _clear_caches drops the in-process layer, so the
        # warm run's exact hits can only come through the disk store
        get_registry().reset(prefix="querycache.")
        _, warm_issues, _ = bench.run_analysis("host")
        bench.check_recall(warm_issues)

        warm_hits = get_query_cache().hits_total()
        warm_stats = get_query_cache().stats()
        assert warm_hits > 0, f"warm run had zero cache hits: {warm_stats}"
        assert warm_stats["disk_reads"] > 0, \
            f"warm hits bypassed the disk store: {warm_stats}"
        assert _issue_keys(cold_issues) == _issue_keys(warm_issues)

        # the hit counters must surface in report meta via observability
        meta = observability_meta()
        assert meta["metrics"]["querycache.lookups"] > 0
        assert sum(
            meta["metrics"][k]
            for k in (
                "querycache.exact_hits",
                "querycache.model_hits",
                "querycache.core_hits",
                "querycache.unknown_hits",
            )
        ) == warm_hits
    finally:
        configure(enabled=True, cache_dir=None)
        reset_query_cache()


def test_query_cache_compare_mode(tmp_path):
    """bench.py --query-cache-compare: the machine-checkable warm-vs-cold
    artifact (asserts internally; shape-checked here)."""
    out = bench.query_cache_compare(str(tmp_path))
    assert out["metric"] == "query_cache_compare"
    assert out["warm_hits"] > 0
    assert 0 < out["warm_hit_rate"] <= 1
    assert out["issues"], "killbilly exploit missing from compare mode"
    assert out["cold"]["stores"] > 0
