"""Static taint reachability: may_reach[source_bit] -> sink opcodes.

A stack/memory-agnostic over-approximation of the frontier's exact row-
graph taint (frontier/taint.py): a source's value can only influence an
instruction that executes *after* the source in some execution, and every
such instruction is CFG-reachable from the source instruction in the
over-approximate CFG.  Memory flows need no modelling — an MLOAD that
observes a tainted MSTORE executes after it, hence is in the closure.

Flows the CFG cannot order are handled by GLOBAL CHANNELS: once a bit
reaches an opcode that can smuggle data out of the current frame's
control order (storage writes, any call/create — re-entry runs this code
from pc 0 in a fresh frame; cross-transaction flows re-read storage), the
bit is escalated to "may reach every reachable sink".  RETURN/REVERT join
the channel set when a call-family op exists (returndata flows back to a
caller frame) and always for creation code (the returned runtime bytecode
itself is a channel — see ROADMAP "Known deviations").
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from mythril_tpu.staticpass.cfg import StaticCFG

CALL_FAMILY = frozenset(
    {"CALL", "CALLCODE", "DELEGATECALL", "STATICCALL", "CREATE", "CREATE2"}
)
GLOBAL_CHANNELS = frozenset({"SSTORE"}) | CALL_FAMILY


def may_reach(
    cfg: StaticCFG,
    block_reach: np.ndarray,
    instr_reach: np.ndarray,
    halting: np.ndarray,
    source_opcodes: Dict[int, str],
    is_creation: bool = False,
) -> Tuple[Dict[int, frozenset], frozenset]:
    """(bit -> reachable-from-source opcode names, escalated bits).

    ``source_opcodes`` maps taint bits to their source opcode (the
    frontier/taint SOURCE_OPCODES registry).  Escalated bits map to every
    opcode on a reachable instruction.
    """
    t = cfg.tables
    all_ops = frozenset(
        t.names[i] for i in range(t.n) if instr_reach[i]
    )
    channels = set(GLOBAL_CHANNELS)
    if is_creation or (all_ops & CALL_FAMILY):
        channels |= {"RETURN", "REVERT"}

    out: Dict[int, frozenset] = {}
    escalated = set()
    for bit, src_op in source_opcodes.items():
        src_blocks = {
            int(cfg.block_id[i])
            for i in range(t.n)
            if instr_reach[i] and t.names[i] == src_op
        }
        if not src_blocks:
            out[bit] = frozenset()
            continue
        # forward closure over the pruned CFG (halting blocks emit nothing)
        seen = np.zeros(cfg.n_blocks, bool)
        stack = [b for b in src_blocks if block_reach[b]]
        for b in stack:
            seen[b] = True
        while stack:
            b = stack.pop()
            if halting[b]:
                continue
            for nb in cfg.succ[b]:
                if block_reach[nb] and not seen[nb]:
                    seen[nb] = True
                    stack.append(nb)
        ops = frozenset(
            t.names[i]
            for b in np.flatnonzero(seen)
            for i in range(int(cfg.block_start[b]), int(cfg.block_end[b]))
            if instr_reach[i]
        )
        if ops & channels:
            escalated.add(bit)
            ops = all_ops
        out[bit] = ops
    return out, frozenset(escalated)
