"""Interval-bound refutation tier (smt/intervals.py): exact-UNSAT claims.

Soundness bar: ``refute() == True`` must NEVER be wrong — a false
refutation is a recall loss in every pruning call site.  Tests pair each
refutation with a solver cross-check and fuzz small widths against brute
force.
"""

import itertools
import random

from mythril_tpu.smt import terms
from mythril_tpu.smt.concrete_eval import Assignment, evaluate
from mythril_tpu.smt.intervals import refute


def bv(name, w=256):
    return terms.var(name, w)


def c(v, w=256):
    return terms.const(v, w)


def test_range_impossible_product_refuted():
    # the motivating shape: loop-exit pins cnt <= 1, overflow demands
    # cnt * value >= 2^256 (512-bit zext-mul)
    cnt, value = bv("cnt"), bv("value")
    p = terms.mul(terms.zext(cnt, 256), terms.zext(value, 256))
    conj = [
        terms.ule(cnt, c(1)),
        terms.ult(c((1 << 256) - 1, 512), p),
    ]
    assert refute(conj)


def test_feasible_product_not_refuted():
    cnt, value = bv("cnt2"), bv("value2")
    p = terms.mul(terms.zext(cnt, 256), terms.zext(value, 256))
    conj = [
        terms.ule(cnt, c(20)),
        terms.ult(c(1, 256), cnt),
        terms.ult(c((1 << 256) - 1, 512), p),
    ]
    assert not refute(conj)  # cnt=2, value=2^255 satisfies


def test_disjoint_eq_ranges_refuted():
    x = bv("x3")
    conj = [terms.ule(x, c(5)), terms.eq(x, c(9))]
    assert refute(conj)


def test_contradictory_bounds_refuted():
    x = bv("x4")
    conj = [terms.ule(x, c(3)), terms.ult(c(7), x)]
    assert refute(conj)


def test_add_bound_propagates():
    # x <= 10 and y <= 10 make x + y > 100 impossible (no wrap at 256 bits)
    x, y = bv("x5"), bv("y5")
    conj = [
        terms.ule(x, c(10)),
        terms.ule(y, c(10)),
        terms.ult(c(100), terms.add(x, y)),
    ]
    assert refute(conj)


def test_wrapping_add_not_refuted():
    # at full range, x + y wraps: the analysis must widen, not refute
    x, y = bv("x6"), bv("y6")
    conj = [terms.ult(c(100), terms.add(x, y))]
    assert not refute(conj)


def test_fuzz_no_false_refutation_width4():
    """Brute-force oracle at width 4: every refuted conjunction must be
    genuinely unsatisfiable."""
    rng = random.Random(1234)
    w = 4
    names = ["a", "b"]

    def rand_term(depth, vars_):
        if depth == 0 or rng.random() < 0.35:
            if rng.random() < 0.5:
                return terms.const(rng.randrange(1 << w), w)
            return vars_[rng.randrange(len(vars_))]
        op = rng.choice([terms.add, terms.sub, terms.mul, terms.band, terms.bor])
        return op(rand_term(depth - 1, vars_), rand_term(depth - 1, vars_))

    refuted = 0
    for _ in range(300):
        vars_ = [terms.var(f"f{rng.randrange(10**9)}", w) for _ in range(2)]
        conj = []
        for _k in range(rng.randrange(1, 4)):
            lhs, rhs = rand_term(2, vars_), rand_term(2, vars_)
            cmp = rng.choice([terms.ult, terms.ule, terms.eq])
            conj.append(cmp(lhs, rhs))
        if not refute(conj):
            continue
        refuted += 1
        # brute-force: no assignment may satisfy all conjuncts
        for vals in itertools.product(range(1 << w), repeat=2):
            asg = Assignment()
            asg.scalars[vars_[0]] = vals[0]
            asg.scalars[vars_[1]] = vals[1]
            out = evaluate(conj, asg)
            assert not all(out[x] for x in conj), (
                f"FALSE refutation: {[str(x) for x in conj]} sat at {vals}"
            )
    # the fuzz must actually exercise refutations to mean anything
    assert refuted >= 5, f"only {refuted} refutations generated"
