"""Top-level plugin discovery/loader (mythril_tpu/plugin/) behavior."""

import pytest

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.module.loader import ModuleLoader
from mythril_tpu.plugin import (
    MythrilPlugin,
    MythrilPluginLoader,
    PluginDiscovery,
    UnsupportedPluginType,
)


class _ToyDetector(DetectionModule, MythrilPlugin):
    name = "ToyDetector"
    swc_id = "000"
    description = "test-only detector"
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["STOP"]

    def _execute(self, state):
        return None


def test_discovery_returns_dict():
    # no external packages install entry points in CI; the API must still work
    discovery = PluginDiscovery()
    assert isinstance(discovery.installed_plugins, dict)
    assert discovery.get_plugins() == list(discovery.installed_plugins)
    assert not discovery.is_installed("definitely-not-installed")
    with pytest.raises(ValueError):
        discovery.build_plugin("definitely-not-installed", {})


def test_loader_routes_detection_module():
    loader = MythrilPluginLoader()
    before = len(ModuleLoader().get_detection_modules())
    plugin = _ToyDetector()
    loader.load(plugin)
    after = ModuleLoader().get_detection_modules()
    assert len(after) == before + 1
    assert plugin in loader.loaded_plugins
    # cleanup: keep the global ModuleLoader stable for other tests
    ModuleLoader()._modules.remove(plugin)


def test_loader_rejects_unknown_type():
    class Odd(MythrilPlugin):
        pass

    with pytest.raises(UnsupportedPluginType):
        MythrilPluginLoader().load(Odd())


def test_execution_info_in_report_meta():
    from mythril_tpu.analysis.report import Report
    from mythril_tpu.core.execution_info import SolverStatsInfo

    report = Report(execution_info=[SolverStatsInfo()])
    import json

    meta = json.loads(report.as_swc_standard_format())[0]["meta"]
    assert "mythril_execution_info" in meta
    assert "solver_query_count" in meta["mythril_execution_info"]


def test_benchmark_plugin_writes_series_and_svg(tmp_path):
    """The benchmark plugin persists its instructions-over-time series as
    JSON plus an SVG chart (the role of the reference's matplotlib png,
    reference benchmark.py:19-94)."""
    import json

    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.support.support_args import args

    out = tmp_path / "bench.json"
    args.benchmark_path = str(out)
    try:
        SymExecWrapper(
            bytes.fromhex("602a60005500"),  # sstore(0, 42); stop
            address=0x0901D12E,
            strategy="dfs",
            transaction_count=1,
            execution_timeout=30,
        )
    finally:
        args.benchmark_path = None
    data = json.loads(out.read_text())
    assert data["executed_instructions"] > 0
    assert len(data["series"]) == data["executed_instructions"]
    svg = (tmp_path / "bench.json.svg").read_text()
    assert svg.startswith("<svg") and svg.rstrip().endswith("</svg>")
    assert "instructions over time" in svg


def test_render_series_svg_empty_series():
    from mythril_tpu.plugins.plugins.benchmark import render_series_svg

    svg = render_series_svg([], title="empty")
    assert svg.startswith("<svg") and svg.endswith("</svg>")


def test_render_series_svg_escapes_markup():
    import xml.etree.ElementTree as ET

    from mythril_tpu.plugins.plugins.benchmark import render_series_svg

    svg = render_series_svg([(0.5, 1)], title="a<b & c>d")
    ET.fromstring(svg)  # must stay well-formed XML


def test_benchmark_long_series_downsampled_not_truncated(tmp_path):
    """>10k points: the persisted series spans the WHOLE run at a stride,
    so the chart's time axis reflects the true duration."""
    import json

    from mythril_tpu.plugins.plugins.benchmark import BenchmarkPlugin

    plugin = BenchmarkPlugin()
    plugin.begin, plugin.end = 0.0, 25.0
    plugin.nr_of_executed_insns = 25_000
    plugin.points = [(i / 1000.0, i + 1) for i in range(25_000)]
    out = tmp_path / "long.json"
    plugin.write_to_file(str(out))
    data = json.loads(out.read_text())
    assert data["executed_instructions"] == 25_000
    assert data["series_stride"] == 3
    assert len(data["series"]) <= 10_001
    assert data["series"][-1] == [24.999, 25_000]  # last point kept
    assert "24" in (tmp_path / "long.json.svg").read_text()  # x axis ~25s


def test_frontier_stats_in_report_meta():
    """--frontier runs surface the park/segment telemetry in jsonv2 meta
    (the data that prioritizes new device handlers, frontier/stats.py)."""
    import json

    from mythril_tpu.analysis.report import Report
    from mythril_tpu.core.execution_info import FrontierStatsInfo
    from mythril_tpu.frontier.stats import FrontierStatistics

    stats = FrontierStatistics()
    stats.reset()
    stats.device_instructions = 123
    stats.record_park("CALL")
    try:
        report = Report(execution_info=[FrontierStatsInfo()])
        meta = json.loads(report.as_swc_standard_format())[0]["meta"]
        frontier = meta["mythril_execution_info"]["frontier"]
        assert frontier["device_instructions"] == 123
        assert frontier["parks_by_opcode"] == {"CALL": 1}
    finally:
        stats.reset()
