"""Pipelined frontier: correction-ledger protocol, the background
feasibility pool, and pipelined-vs-synchronous issue-set parity.

The parity tests mirror test_frontier_engine's differential idiom — the
synchronous loop is the oracle, the pipelined loop must produce the same
issues (the ISSUE's correctness bar, same contract as --no-staticpass).
"""

from pathlib import Path

import numpy as np
import pytest

from mythril_tpu.frontier.pipeline import CorrectionLedger, FeasibilityPool
from mythril_tpu.support.support_args import args as global_args

TESTDATA = Path(__file__).parent.parent / "testdata" / "inputs"


# ---------------------------------------------------------------------------
# CorrectionLedger
# ---------------------------------------------------------------------------


def test_ledger_touch_rides_next_dispatch():
    led = CorrectionLedger(4)
    led.touch(1)
    assert led.corr_mask[1] and led.active_at[1] == 0
    mask = led.consume(np.array([0, 0, 0, 0]))
    assert mask[1] and mask.sum() == 1
    assert not led.corr_mask.any(), "mask must clear after consume"
    # dispatch 0 pulled: slot 1's host write rode dispatch 0, so its
    # output IS authoritative — nothing to carry
    assert list(led.on_pull()) == []


def test_ledger_carry_until_active_dispatch_pulled():
    led = CorrectionLedger(4)
    led.consume(np.full(4, -1))  # dispatch 0 issued before the touch
    led.touch(2)  # rides dispatch 1
    led.consume(np.full(4, -1))  # dispatch 1 issued
    # pulling dispatch 0: slot 2's write is newer than this output
    assert list(led.on_pull()) == [2]
    # pulling dispatch 1: now the device output reflects the write
    assert list(led.on_pull()) == []


def test_ledger_device_ownership_of_freed_slots():
    led = CorrectionLedger(4)
    host_seed = np.array([5, -1, 7, -1])  # slots 1 and 3 are free
    led.touch(1)  # freed by the host
    led.touch(2)  # live correction
    led.consume(host_seed)
    assert led.device_owned[1], "freed slot exposed to device must be owned"
    assert not led.device_owned[2], "live slot is not grantable"
    assert not led.device_owned[3], "untouched free slot was never exposed"
    led.release_owned()
    assert not led.device_owned.any()


def test_ledger_consume_all_marks_everything():
    led = CorrectionLedger(3)
    led.touch(0)
    led.consume_all()
    assert (led.active_at == 0).all()
    assert not led.corr_mask.any()
    assert list(led.on_pull()) == []


def test_ledger_carry_forward_clears_events():
    from mythril_tpu.frontier.state import empty_state
    from mythril_tpu.frontier.step import Caps

    caps = Caps(B=4)
    prev = empty_state(caps, 4)
    new = empty_state(caps, 4)
    prev.pc[1] = 42
    prev.seed[1] = 9
    new.pc[1] = 7  # stale device value
    new.ev_len[1] = 3  # stale device events

    led = CorrectionLedger(4)
    led.consume(np.full(4, -1))  # dispatch 0 (before the host write)
    led.touch(1)
    led.consume(np.full(4, -1))  # dispatch 1 carries the write
    carried = led.carry_forward(new, prev)  # pull of dispatch 0
    assert carried == 1
    assert new.pc[1] == 42 and new.seed[1] == 9
    assert new.ev_len[1] == 0, "carried slots must not re-drain old events"


# ---------------------------------------------------------------------------
# FeasibilityPool
# ---------------------------------------------------------------------------


def _sym_neq(value: int):
    from mythril_tpu.smt import terms

    x = terms.var("pool_x", 256)
    return terms.not_(terms.eq(x, terms.const(value, 256)))


def test_pool_sat_and_unsat_verdicts():
    from mythril_tpu.smt import terms

    pool = FeasibilityPool(workers=2)
    x = terms.var("pool_y", 256)
    sat_raws = [terms.eq(x, terms.const(5, 256))]
    unsat_raws = [
        terms.eq(x, terms.const(1, 256)),
        terms.eq(x, terms.const(2, 256)),
    ]
    pool.submit(0, "recA", 1, sat_raws, frozenset(t.tid for t in sat_raws))
    pool.submit(1, "recB", 2, unsat_raws,
                frozenset(t.tid for t in unsat_raws))
    pool._executor.shutdown(wait=True)
    verdicts = {slot: ok for slot, rec, n, ok, why in pool.drain()}
    assert verdicts == {0: True, 1: False}
    assert pool.pending() == 0


def test_pool_inflight_dedup_fans_out_one_solve():
    from mythril_tpu.observability.metrics import get_registry
    from mythril_tpu.smt import terms

    get_registry().reset(prefix="pipeline.")
    pool = FeasibilityPool(workers=1)
    x = terms.var("pool_z", 256)
    raws = [terms.eq(x, terms.const(3, 256))]
    key = frozenset(t.tid for t in raws)
    # hold the solver lock so both submits land before the worker runs
    with pool._solver_lock:
        pool.submit(0, "recA", 1, raws, key)
        pool.submit(1, "recB", 1, raws, key)
    pool._executor.shutdown(wait=True)
    out = sorted((slot, ok) for slot, rec, n, ok, why in pool.drain())
    assert out == [(0, True), (1, True)], "both waiters get the verdict"
    reg = get_registry()
    assert reg.counter("pipeline.pool_inflight_dedup").value == 1
    assert reg.counter("pipeline.pool_submitted").value == 1


# ---------------------------------------------------------------------------
# pipelined vs synchronous parity (differential, device forced on)
# ---------------------------------------------------------------------------


def _analyze(code: bytes, tx_count: int, modules, pipeline: bool):
    from mythril_tpu.analysis.module.loader import ModuleLoader
    from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
    from mythril_tpu.analysis.symbolic import SymExecWrapper

    reset_callback_modules()
    for m in ModuleLoader().get_detection_modules():
        if hasattr(m, "cache"):
            m.cache.clear()
    prev = (global_args.frontier, global_args.frontier_force,
            global_args.frontier_mesh, global_args.pipeline)
    global_args.frontier = True
    global_args.frontier_force = True
    # the harness pins an 8-device virtual CPU mesh (conftest); the
    # pipelined runner is a single-device path, so compare apples to
    # apples with the mesh disabled in both modes
    global_args.frontier_mesh = False
    global_args.pipeline = pipeline
    try:
        sym = SymExecWrapper(
            code,
            address=0x0901D12E,
            strategy="dfs",
            transaction_count=tx_count,
            execution_timeout=120,
            modules=modules,
        )
        return fire_lasers(sym, white_list=modules)
    finally:
        (global_args.frontier, global_args.frontier_force,
         global_args.frontier_mesh, global_args.pipeline) = prev


def _issue_keys(issues):
    return sorted((i.swc_id, i.address, i.function) for i in issues)


@pytest.mark.slow
def test_pipeline_parity_testdata_contracts():
    from mythril_tpu.observability.metrics import get_registry

    code = bytes.fromhex(
        (TESTDATA / "kill_simple.bin-runtime").read_text().strip()
    )
    get_registry().reset(prefix="pipeline.")
    piped = _analyze(code, 1, ["AccidentallyKillable"], pipeline=True)
    snap = get_registry().snapshot(prefix="pipeline.")
    sync = _analyze(code, 1, ["AccidentallyKillable"], pipeline=False)
    assert _issue_keys(piped) == _issue_keys(sync)
    assert len(piped) == 1
    assert snap.get("pipeline.segments_pipelined", 0) > 0, (
        f"pipelined run never chained a dispatch: {snap}"
    )


@pytest.mark.slow
def test_pipeline_parity_multi_tx_storage_gate():
    # storage-gated selfdestruct: needs the 2-tx chain and exercises
    # harvest-driven slot recycling across pipelined segments
    from tests.frontier.test_frontier_engine import DISPATCH

    guarded = DISPATCH + "600054600114601b5733ff5b00"
    code = bytes.fromhex(guarded)
    piped = _analyze(code, 2, ["AccidentallyKillable"], pipeline=True)
    sync = _analyze(code, 2, ["AccidentallyKillable"], pipeline=False)
    assert _issue_keys(piped) == _issue_keys(sync)
