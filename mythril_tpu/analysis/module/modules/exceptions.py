"""Exceptions: reachable assert-fail / INVALID opcode (SWC-110).

Reference parity: mythril/analysis/module/modules/exceptions.py:1-136.
"""

from __future__ import annotations

from typing import List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import ASSERT_VIOLATION
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.exceptions import UnsatError

DESCRIPTION = """
Checks whether any exception states are reachable.
"""


class Exceptions(DetectionModule):
    name = "Assertion violation"
    swc_id = ASSERT_VIOLATION
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["INVALID"]

    def _execute(self, state: GlobalState) -> Optional[List[Issue]]:
        if self._cache_key(state) in self.cache:
            return None
        return self._analyze_state(state)

    def _analyze_state(self, state: GlobalState) -> List[Issue]:
        # solve immediately: the INVALID halts this path exceptionally, so a
        # deferred (tx-end) check would never fire for it
        instruction = state.get_current_instruction()
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints.get_all_constraints()
            )
        except UnsatError:
            return []
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.node.function_name if state.node else "unknown",
                address=instruction["address"],
                swc_id=ASSERT_VIOLATION,
                title="Exception State",
                severity="Medium",
                bytecode=state.environment.code.bytecode,
                description_head="An assertion violation was triggered.",
                description_tail=(
                    "It is possible to trigger an assertion violation. Note that "
                    "Solidity assert() statements should only be used to check "
                    "invariants. Review the transaction sequence to see if this "
                    "condition can be triggered by user input."
                ),
                gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
                transaction_sequence=transaction_sequence,
            )
        ]


detector = Exceptions
