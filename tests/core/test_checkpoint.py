"""Checkpoint/resume: frontier snapshots round-trip through disk."""

import pytest

from mythril_tpu.smt import terms
from mythril_tpu.smt.serialize import dump_terms, load_terms


def test_term_roundtrip_restores_sharing():
    x = terms.var("ckx", 256)
    y = terms.var("cky", 256)
    shared = terms.add(x, y)
    roots = [
        terms.eq(shared, terms.const(7, 256)),
        terms.ult(shared, terms.keccak(x)),
        terms.extract(15, 8, y),
    ]
    data = dump_terms(roots)
    # force JSON round-trip (the on-disk representation)
    import json

    data = json.loads(json.dumps(data))
    back = load_terms(data)
    # interning means reloaded roots ARE the original terms
    assert all(a is b for a, b in zip(roots, back))


def test_nested_aux_roundtrip():
    # 'apply' aux is (name, (widths...), out_width): the nested tuple must
    # survive JSON or re-interning raises on the unhashable inner list
    x = terms.var("ckax", 8)
    y = terms.var("ckay", 8)
    f = terms.apply_func("ckf", 256, x, y)
    import json

    data = json.loads(json.dumps(dump_terms([f])))
    assert load_terms(data)[0] is f


def test_world_state_checkpoint_roundtrip(tmp_path):
    from mythril_tpu.core.state.account import Account
    from mythril_tpu.core.state.world_state import WorldState
    from mythril_tpu.frontend.disassembler import Disassembly
    from mythril_tpu.smt import symbol_factory
    from mythril_tpu.support.checkpoint import load_checkpoint, save_checkpoint

    ws = WorldState()
    acct = Account(0xAABB, code=Disassembly("6001600101"), nonce=3)
    ws.put_account(acct)
    acct.set_balance(10**18)
    key = symbol_factory.BitVecVal(5, 256)
    acct.storage[key] = symbol_factory.BitVecVal(42, 256)
    sym = symbol_factory.BitVecSym("slot", 256)
    acct.storage[sym] = symbol_factory.BitVecVal(9, 256)
    ws.constraints.append(
        symbol_factory.BitVecSym("z", 256) == symbol_factory.BitVecVal(1, 256)
    )

    path = str(tmp_path / "ckpt.json")
    save_checkpoint(path, completed_transactions=1, open_states=[ws])
    done, states, _addr = load_checkpoint(path)

    assert done == 1
    assert len(states) == 1
    restored = states[0]
    racct = restored.accounts[0xAABB]
    assert racct.nonce == 3
    assert racct.code.bytecode == bytes.fromhex("6001600101")
    # interning identity: the restored storage array IS the original term,
    # store chain included (reads behave exactly as before the snapshot)
    assert racct.storage._array.raw is acct.storage._array.raw
    assert racct.storage[sym].value == 9
    assert len(restored.constraints) == 1
    # balances array round-trips as the same interned term
    assert restored.balances.raw is ws.balances.raw


def test_resume_continues_analysis(tmp_path):
    """Interrupt after tx 1 of killbilly, resume, and still find the issue."""
    import time

    from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.analysis.module.loader import ModuleLoader

    import bench  # killbilly bytecode fixtures live in the benchmark

    for module in ModuleLoader().get_detection_modules():
        module.cache.clear()
    reset_callback_modules()

    ckpt = str(tmp_path / "frontier.json")
    contract = EVMContract(
        code=bench.KILLBILLY, creation_code=bench.KILLBILLY_CREATION, name="KB"
    )
    # phase 1: run only the first transaction, checkpointing the frontier
    sym = SymExecWrapper(
        contract,
        address=0x0901D12E,
        strategy="bfs",
        transaction_count=1,
        execution_timeout=120,
        modules=["AccidentallyKillable"],
        checkpoint_path=ckpt,
    )
    import os

    assert os.path.exists(ckpt)

    # phase 2: resume from the snapshot and run the remaining transaction
    for module in ModuleLoader().get_detection_modules():
        module.cache.clear()
    reset_callback_modules()
    sym2 = SymExecWrapper(
        contract,
        address=0x0901D12E,
        strategy="bfs",
        transaction_count=2,
        execution_timeout=120,
        modules=["AccidentallyKillable"],
        resume_from=ckpt,
    )
    issues = fire_lasers(sym2, white_list=["AccidentallyKillable"])
    assert issues and issues[0].swc_id == "106"
