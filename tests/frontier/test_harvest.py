"""Sharded harvest executor: vectorized ingestion vs the serial reference,
laser-affinity of the replay pool, delta pulls, and serial-vs-sharded
issue-set parity.

The vectorized decoder and the replay pool are performance rewrites of
engine._harvest's inner loops; every test here pins them to the serial
semantics they replaced — the ingestion test differentially against an
inline reimplementation of the old slot-order rescan loop, the parity
tests end-to-end against ``--harvest-workers 0``.
"""

import threading
from pathlib import Path

import numpy as np
import pytest

from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier.harvest import (
    HarvestExecutor,
    ingest_events,
    shutdown_replay_pool,
)
from mythril_tpu.frontier.records import PathRecord
from mythril_tpu.frontier.state import Caps, empty_state
from mythril_tpu.support.support_args import args as global_args

TESTDATA = Path(__file__).parent.parent / "testdata" / "inputs"


# ---------------------------------------------------------------------------
# vectorized ingestion vs the serial reference
# ---------------------------------------------------------------------------


def _serial_ingest(st, records, ev_seen):
    """The pre-executor engine._harvest step 1, verbatim: slot-order scan
    repeated until no new record appears."""
    B = st.events.shape[0]
    changed = True
    while changed:
        changed = False
        for slot in range(B):
            rec = records[slot]
            if rec is None:
                continue
            n_ev = int(st.ev_len[slot])
            for k in range(int(ev_seen[slot]), n_ev):
                ev = st.events[slot, k].copy()
                ev_idx = len(rec.events)
                rec.events.append(ev)
                if (int(ev[O.EV_KIND]) == O.E_FORK
                        and int(ev[O.EV_EXTRA]) >= 0):
                    child_slot = int(ev[O.EV_EXTRA])
                    child = PathRecord(
                        seed_idx=rec.seed_idx, parent=rec,
                        fork_event_idx=ev_idx,
                    )
                    rec.children_by_event[ev_idx] = child
                    records[child_slot] = child
                    ev_seen[child_slot] = 0
                    changed = True
            ev_seen[slot] = n_ev


def _hook_event(pc):
    ev = np.full(O.EV_W, -1, np.int64)
    ev[O.EV_KIND] = O.E_HOOK
    ev[O.EV_PC] = pc
    return ev


def _fork_event(pc, child_slot):
    ev = np.full(O.EV_W, -1, np.int64)
    ev[O.EV_KIND] = O.E_FORK
    ev[O.EV_PC] = pc
    ev[O.EV_EXTRA] = child_slot
    return ev


def _put_events(st, slot, events):
    for k, ev in enumerate(events):
        st.events[slot, k] = ev
    st.ev_len[slot] = len(events)


def _fixture_state(caps):
    """Slot 0 forks into slot 2 which forks (same segment) into slot 5 —
    the chain the old ``while changed`` rescan existed for — plus an
    unrelated path in slot 1 and a dead single-branch fork row."""
    st = empty_state(caps, 4)
    records = {i: None for i in range(caps.B)}
    records[0] = PathRecord(seed_idx=0)
    records[1] = PathRecord(seed_idx=1)
    for s in (0, 1, 2, 5):
        st.seed[s] = 0 if s != 1 else 1
        st.halt[s] = O.H_RUNNING
    _put_events(st, 0, [_hook_event(3), _fork_event(7, 2), _hook_event(9)])
    _put_events(st, 1, [_hook_event(4), _fork_event(6, -1)])  # single-branch
    _put_events(st, 2, [_hook_event(8), _fork_event(11, 5)])  # child forks
    _put_events(st, 5, [_hook_event(12)])  # grandchild, same segment
    return st, records


def _record_shape(records):
    out = {}
    for slot, rec in records.items():
        if rec is None:
            continue
        out[slot] = {
            "seed": rec.seed_idx,
            "fork_event_idx": rec.fork_event_idx,
            "parent": next(
                (s for s, r in records.items() if r is rec.parent), None
            ),
            "events": [tuple(int(x) for x in ev) for ev in rec.events],
            "children": sorted(rec.children_by_event.keys()),
        }
    return out


def test_fork_chain_ingestion_matches_serial_reference():
    caps = Caps(B=8)
    st_a, rec_a = _fixture_state(caps)
    st_b, rec_b = _fixture_state(caps)
    seen_a = np.zeros(caps.B, np.int64)
    seen_b = np.zeros(caps.B, np.int64)

    ingest_events(st_a, rec_a, seen_a)
    _serial_ingest(st_b, rec_b, seen_b)

    assert _record_shape(rec_a) == _record_shape(rec_b)
    assert np.array_equal(seen_a, seen_b)
    # the chain resolved: grandchild record exists with correct lineage
    assert rec_a[5].parent is rec_a[2]
    assert rec_a[2].parent is rec_a[0]
    assert rec_a[2].fork_event_idx == 1  # second event of slot 0's stream
    assert rec_a[0].children_by_event[1] is rec_a[2]


def test_ingestion_resumes_from_ev_seen():
    """A second harvest of the same segment must only append the unseen
    suffix (the pipelined loop re-enters with nonzero ev_seen)."""
    caps = Caps(B=4)
    st = empty_state(caps, 4)
    records = {i: None for i in range(caps.B)}
    records[0] = PathRecord(seed_idx=0)
    st.seed[0] = 0
    _put_events(st, 0, [_hook_event(1), _hook_event(2), _hook_event(3)])
    ev_seen = np.zeros(caps.B, np.int64)
    st.ev_len[0] = 2
    ingest_events(st, records, ev_seen)
    assert len(records[0].events) == 2 and ev_seen[0] == 2
    st.ev_len[0] = 3
    ingest_events(st, records, ev_seen)
    assert len(records[0].events) == 3 and ev_seen[0] == 3
    assert [int(e[O.EV_PC]) for e in records[0].events] == [1, 2, 3]


# ---------------------------------------------------------------------------
# seed affinity: one worker per laser, slot order within it
# ---------------------------------------------------------------------------


class _Rec(PathRecord):
    """PathRecord plus a slot breadcrumb (the real class has __slots__)."""

    __slots__ = ("_slot",)


class _Laser:
    def __init__(self):
        self.work_list = []
        self.total_states = 0


class _AffinityWalker:
    """Instrumented walker: records which thread replays each record."""

    def __init__(self, lasers, seed_laser):
        self.lasers = [seed_laser[i] for i in range(len(seed_laser))]
        self._all = lasers
        self.by_laser = {id(l): [] for l in lasers}
        self.lock = threading.Lock()
        self.committed = []

    def laser_for(self, rec):
        return self.lasers[rec.seed_idx]

    def replay(self, rec):
        with self.lock:
            self.by_laser[id(self.laser_for(rec))].append(
                (threading.get_ident(), rec._slot)
            )

    def commit(self, rec):
        self.committed.append(rec._slot)


class _FakeEngine:
    def __init__(self, caps):
        self.caps = caps

    def _prune_running(self, st, records, walker, ev_seen, pipe=None):
        pass

    def _prefetch_mutation_checks(self, st, records, walker):
        pass


def test_replay_shards_have_laser_affinity_and_slot_order():
    caps = Caps(B=16)
    lasers = [_Laser(), _Laser(), _Laser()]
    # seeds 0,3 -> laser 0; 1,4 -> laser 1; 2,5 -> laser 2 (interleaved,
    # like a multi-selector corpus batch)
    seed_laser = {i: lasers[i % 3] for i in range(6)}
    walker = _AffinityWalker(lasers, seed_laser)
    st = empty_state(caps, 4)
    records = {i: None for i in range(caps.B)}
    for slot in range(12):
        seed = slot % 6
        rec = _Rec(seed_idx=seed)
        rec._slot = slot
        records[slot] = rec
        st.seed[slot] = seed
        st.halt[slot] = O.H_STOP  # every path finished
    try:
        HarvestExecutor(_FakeEngine(caps), workers=4).harvest(
            st, records, walker, np.zeros(caps.B, np.int64)
        )
    finally:
        shutdown_replay_pool()
    for laser in lasers:
        replays = walker.by_laser[id(laser)]
        assert replays, "every laser received finishing paths"
        threads = {t for t, _ in replays}
        assert len(threads) == 1, (
            f"laser touched by {len(threads)} worker threads"
        )
        slots = [s for _, s in replays]
        assert slots == sorted(slots), "shard must replay in slot order"
    # commit stays on the calling thread, in global slot order
    assert walker.committed == sorted(walker.committed)
    assert len(walker.committed) == 12
    assert all(records[s] is None for s in range(12)), "slots recycled"


def test_serial_escape_hatch_uses_no_pool():
    caps = Caps(B=4)
    lasers = [_Laser()]
    walker = _AffinityWalker(lasers, {0: lasers[0]})
    st = empty_state(caps, 4)
    records = {i: None for i in range(caps.B)}
    rec = _Rec(seed_idx=0)
    rec._slot = 0
    records[0] = rec
    st.seed[0] = 0
    st.halt[0] = O.H_RETURN
    HarvestExecutor(_FakeEngine(caps), workers=0).harvest(
        st, records, walker, np.zeros(caps.B, np.int64)
    )
    (replays,) = walker.by_laser[id(lasers[0])]
    assert replays[0] == threading.get_ident(), "workers=0 replays inline"


# ---------------------------------------------------------------------------
# term interning under concurrent replay
# ---------------------------------------------------------------------------


def test_intern_table_is_race_free_under_threads():
    from mythril_tpu.smt import terms

    results = [[] for _ in range(8)]

    def mint(out):
        for i in range(200):
            x = terms.var("race_x%d" % (i % 10), 256)
            out.append(terms.add(x, terms.const(i % 7, 256)))

    threads = [
        threading.Thread(target=mint, args=(out,)) for out in results
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # identical (op, args, aux) keys must be the SAME object across threads
    for other in results[1:]:
        for a, b in zip(results[0], other):
            assert a is b, "interning minted duplicate terms under threads"


# ---------------------------------------------------------------------------
# serial vs sharded end-to-end parity (differential, device forced on)
# ---------------------------------------------------------------------------


def _analyze(code: bytes, tx_count: int, modules, harvest_workers: int):
    from mythril_tpu.analysis.module.loader import ModuleLoader
    from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
    from mythril_tpu.analysis.symbolic import SymExecWrapper

    reset_callback_modules()
    for m in ModuleLoader().get_detection_modules():
        if hasattr(m, "cache"):
            m.cache.clear()
    prev = (global_args.frontier, global_args.frontier_force,
            global_args.frontier_mesh, global_args.harvest_workers)
    global_args.frontier = True
    global_args.frontier_force = True
    global_args.frontier_mesh = False
    global_args.harvest_workers = harvest_workers
    try:
        sym = SymExecWrapper(
            code,
            address=0x0901D12E,
            strategy="dfs",
            transaction_count=tx_count,
            execution_timeout=120,
            modules=modules,
        )
        return fire_lasers(sym, white_list=modules)
    finally:
        (global_args.frontier, global_args.frontier_force,
         global_args.frontier_mesh, global_args.harvest_workers) = prev


def _issue_keys(issues):
    return sorted((i.swc_id, i.address, i.function) for i in issues)


def _frontier_marks():
    """Park stamps + path counts: the harvest-visible side effects the
    sharded executor must reproduce bit-for-bit."""
    from mythril_tpu.frontier.stats import FrontierStatistics

    s = FrontierStatistics()
    return {
        "parks_by_opcode": dict(s.parks_by_opcode.most_common()),
        "parks_by_reason": dict(s.parks_by_reason.most_common()),
        "device_paths": s.device_paths,
        "semantic_parks": s.semantic_parks,
    }


def _run_marked(code, txs, modules, workers):
    from mythril_tpu.observability.metrics import get_registry

    get_registry().reset(prefix="frontier.")
    issues = _analyze(code, txs, modules, workers)
    return _issue_keys(issues), _frontier_marks()


def _fork_heavy() -> bytes:
    """8 reconvergent symbolic branches (256 concurrent paths) ending in an
    unguarded SELFDESTRUCT: every path is a terminal replay, the shape that
    maximizes replay-pool pressure."""
    out = b""
    for k in range(8):
        dest = len(out) + 10
        out += bytes([0x60, k, 0x35, 0x60, 0x01, 0x16,
                      0x61, (dest >> 8) & 0xFF, dest & 0xFF, 0x57, 0x5B])
    return out + bytes([0x33, 0xFF])


@pytest.mark.slow
def test_harvest_parity_fork_heavy():
    code = _fork_heavy()
    serial_issues, serial_marks = _run_marked(
        code, 1, ["AccidentallyKillable"], 0
    )
    assert any(s == "106" for s, _, _ in serial_issues)
    for workers in (1, 4):
        issues, marks = _run_marked(
            code, 1, ["AccidentallyKillable"], workers
        )
        assert issues == serial_issues, (
            f"workers={workers} changed the issue set"
        )
        assert marks == serial_marks, (
            f"workers={workers} changed park stamps/path counts: "
            f"{marks} != {serial_marks}"
        )


@pytest.mark.slow
def test_harvest_parity_multi_tx_storage_gate():
    # storage-gated selfdestruct: the 2-tx chain exercises park-carrier
    # restore and slot recycling across harvests
    from tests.frontier.test_frontier_engine import DISPATCH

    guarded = DISPATCH + "600054600114601b5733ff5b00"
    code = bytes.fromhex(guarded)
    serial_issues, serial_marks = _run_marked(
        code, 2, ["AccidentallyKillable"], 0
    )
    sharded_issues, sharded_marks = _run_marked(
        code, 2, ["AccidentallyKillable"], 4
    )
    assert sharded_issues == serial_issues
    assert sharded_marks == serial_marks


# ---------------------------------------------------------------------------
# delta pulls: bit-identical mirror vs the full pull
# ---------------------------------------------------------------------------


def test_delta_pull_matches_full_pull():
    import jax.numpy as jnp

    from mythril_tpu.frontier.step import pull_harvest, push_state

    caps = Caps(B=8)
    st = empty_state(caps, 4)
    for s in range(4):
        st.seed[s] = s
        st.halt[s] = O.H_RUNNING
        st.pc[s] = 10 + s
        st.stack[s, :2] = [100 + s, 200 + s]
        st.stack_len[s] = 2
        st.cons[s, 0] = 7
        st.cons_len[s] = 1
    st.halt[2] = O.H_STOP  # finishing slot: its rows must be re-pulled
    st.events[1, 0, :] = 5
    st.ev_len[1] = 1
    st.cons[3, 1] = 9
    st.cons_len[3] = 2  # grew since the previous pull

    dev = push_state(st)
    dev = dev._replace(
        events=jnp.asarray(st.events), ev_len=jnp.asarray(st.ev_len)
    )
    full = pull_harvest(dev, 17, 55, 3)

    # previous mirror: stale where the device advanced
    prev = empty_state(caps, 4)
    for name, dst, src in zip(prev._fields, prev, full[0]):
        if name != "events":
            dst[...] = src
    prev.cons_len[3] = 1
    prev.cons[3, 1] = -1
    prev.stack[2] = -1
    prev.ev_len[1] = 0

    delta = pull_harvest(dev, 17, 55, 3, prev=prev)
    assert delta[1:] == full[1:]
    for name, a, b in zip(full[0]._fields, full[0], delta[0]):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"delta pull diverged from full pull in {name}"
        )
