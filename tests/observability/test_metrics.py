"""Metrics registry: counters/gauges/histograms, persistent scope, snapshot."""

import pytest

from mythril_tpu.observability.metrics import (
    Counter,
    Histogram,
    MetricsRegistry,
)


@pytest.fixture
def reg():
    return MetricsRegistry()


def test_counter_inc_set_reset(reg):
    c = reg.counter("t.count")
    c.inc()
    c.inc(4)
    assert c.value == 5
    c.set(2)
    assert c.value == 2
    c.reset()
    assert c.value == 0


def test_float_counter_keeps_type_through_reset(reg):
    c = reg.counter("t.wall_s", initial=0.0)
    c.inc(1.5)
    c.reset()
    assert c.value == 0.0 and isinstance(c.value, float)


def test_gauge_object_default_not_shared_across_resets(reg):
    g = reg.gauge("t.bench", default={})
    g.value["k"] = 1
    g.reset()
    assert g.value == {}
    g.value["j"] = 2
    g.reset()
    assert g.value == {}


def test_labeled_counter_behaves_like_counter(reg):
    lc = reg.labeled_counter("t.parks")
    lc["CALL"] += 2
    lc["SHA3"] += 1
    assert lc.most_common()[0] == ("CALL", 2)
    assert reg.snapshot()["t.parks"] == {"CALL": 2, "SHA3": 1}
    lc.reset()
    assert dict(lc) == {}


def test_histogram_bucketing(reg):
    h = reg.histogram("t.lat", buckets=(0.001, 0.01, 0.1, 1.0))
    for v in (0.0005, 0.005, 0.005, 0.05, 5.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(5.0605)
    assert h.min == pytest.approx(0.0005)
    assert h.max == pytest.approx(5.0)
    # one slot per observation: <=0.001, <=0.01 (x2), <=0.1, +Inf overflow
    assert h.bucket_counts == [1, 2, 1, 0, 1]
    snap = h.snapshot()
    assert snap["buckets_le"] == {"0.001": 1, "0.01": 2, "0.1": 1, "+Inf": 1}
    assert snap["avg"] == pytest.approx(5.0605 / 5)


def test_histogram_boundary_lands_in_le_bucket(reg):
    h = reg.histogram("t.edge", buckets=(1.0, 2.0))
    h.observe(1.0)  # exactly on the bound counts as <= bound
    assert h.bucket_counts == [1, 0, 0]


def test_registry_get_or_create_returns_same_instance(reg):
    assert reg.counter("t.a") is reg.counter("t.a")
    with pytest.raises(TypeError):
        reg.gauge("t.a")  # name already taken by a counter


def test_persistent_scope_survives_reset(reg):
    reg.counter("t.per_analysis").inc(7)
    reg.counter("t.verdicts", persistent=True).inc(3)
    reg.reset()
    assert reg.counter("t.per_analysis").value == 0
    assert reg.counter("t.verdicts", persistent=True).value == 3
    reg.reset(include_persistent=True)
    assert reg.counter("t.verdicts", persistent=True).value == 0


def test_reset_prefix_scopes_the_sweep(reg):
    reg.counter("a.x").inc()
    reg.counter("b.y").inc()
    reg.reset(prefix="a.")
    assert reg.counter("a.x").value == 0
    assert reg.counter("b.y").value == 1


def test_snapshot_is_json_serializable(reg):
    import json

    reg.counter("t.c").inc()
    reg.gauge("t.g", default={}).set({"k": [1, 2]})
    reg.histogram("t.h").observe(0.2)
    reg.labeled_counter("t.l")["OP"] += 1
    json.dumps(reg.snapshot())  # must not raise


def test_counter_metric_snapshot_is_plain_value():
    c = Counter("x")
    c.inc(3)
    assert c.snapshot() == 3
    h = Histogram("y")
    assert h.snapshot() == {"count": 0, "sum": 0.0}


def test_histogram_percentile_interpolation(reg):
    h = reg.histogram("t.lat", buckets=(1.0, 2.0, 4.0))
    assert h.percentile(0.5) is None  # empty
    for v in (0.5, 1.5, 1.7, 3.0, 9.0):
        h.observe(v)
    # clamped to the observed range at the extremes
    assert h.percentile(0.0) == 0.5
    assert h.percentile(1.0) == 9.0
    # rank 2.5 of 5 lands mid-way through the (1, 2] bucket's two obs
    assert h.percentile(0.50) == pytest.approx(1.75)
    # rank 4.75 interpolates the +Inf bucket up to the observed max
    assert h.percentile(0.95) == pytest.approx(7.75)
    # monotone in q
    qs = [h.percentile(q) for q in (0.1, 0.5, 0.9, 0.99)]
    assert qs == sorted(qs)


def test_prometheus_text_exposition(reg):
    from mythril_tpu.observability.metrics import prometheus_text

    reg.counter("svc.requests").inc(3)
    reg.labeled_counter(
        "svc.tenant_requests", label_name="tenant"
    ).inc("a-corp", 2)
    h = reg.histogram("svc.wait_s", buckets=(1.0, 2.0))
    h.observe(0.5)
    h.observe(3.0)
    reg.gauge("svc.depth", default=0).set(7)
    reg.gauge("svc.shards", default={}).set({"s0": 4, "note": "text"})
    reg.gauge("svc.blob", default=None).set("not-exposable")
    text = prometheus_text(reg)
    assert "# TYPE svc_requests counter\nsvc_requests 3" in text
    assert 'svc_tenant_requests{tenant="a-corp"} 2' in text
    # cumulative buckets + sum/count
    assert 'svc_wait_s_bucket{le="1.0"} 1' in text
    assert 'svc_wait_s_bucket{le="2.0"} 1' in text
    assert 'svc_wait_s_bucket{le="+Inf"} 2' in text
    assert "svc_wait_s_sum 3.5" in text
    assert "svc_wait_s_count 2" in text
    assert "svc_depth 7" in text
    # dict gauges keep numeric keys only; non-numeric gauges are skipped
    assert 'svc_shards{key="s0"} 4' in text
    assert "note" not in text and "blob" not in text
    # names are sanitized to the exposition charset
    assert "svc.requests" not in text


def test_prometheus_label_values_escaped(reg):
    """Regression: label *values* are interpolated into the exposition
    inside double quotes, so backslash, quote and newline must be
    escaped per the text-format spec or one hostile tenant label breaks
    the whole scrape."""
    from mythril_tpu.observability.metrics import prometheus_text

    reg.labeled_counter("svc.tenant_requests", label_name="tenant").inc(
        'evil"corp\\with\nnewline', 1
    )
    text = prometheus_text(reg)
    line = next(
        l for l in text.splitlines()
        if l.startswith("svc_tenant_requests{")
    )
    assert line == (
        'svc_tenant_requests{tenant="evil\\"corp\\\\with\\nnewline"} 1'
    )
    # the exposition stays one-sample-per-line: no raw newline leaked
    assert all(
        l.startswith(("#", "svc_")) for l in text.splitlines() if l
    )


def test_prometheus_label_names_sanitized(reg):
    """A label *name* is interpolated verbatim (it cannot be quoted), so
    it is sanitized to the identifier charset like metric names are."""
    from mythril_tpu.observability.metrics import prometheus_text

    reg.labeled_counter("svc.by_kind", label_name="kind-of.thing").inc(
        "x", 2
    )
    text = prometheus_text(reg)
    assert 'svc_by_kind{kind_of_thing="x"} 2' in text


def test_percentile_edge_cases(reg):
    from mythril_tpu.observability.metrics import percentile_from_buckets

    h = reg.histogram("t.edge", buckets=(1.0, 2.0, 4.0))
    # empty histogram has no quantiles at all
    assert h.percentile(0.5) is None
    assert h.percentile(0.0) is None

    # single observation: every quantile is that observation
    h.observe(1.5)
    for q in (0.0, 0.5, 0.95, 1.0):
        assert h.percentile(q) == pytest.approx(1.5)

    # q outside [0, 1] clamps instead of extrapolating
    assert h.percentile(-3.0) == h.percentile(0.0)
    assert h.percentile(7.0) == h.percentile(1.0)

    # all mass in one bucket: estimates stay inside that bucket and
    # clamp to the observed extremes
    h2 = reg.histogram("t.edge2", buckets=(1.0, 2.0, 4.0))
    for v in (1.2, 1.4, 1.8):
        h2.observe(v)
    for q in (0.1, 0.5, 0.9):
        assert 1.2 <= h2.percentile(q) <= 1.8

    # the module function mirrors Histogram.percentile exactly (the
    # history window estimator depends on this)
    assert percentile_from_buckets(
        (1.0, 2.0, 4.0), [0, 3, 0, 0], 0.5, lo_obs=1.2, hi_obs=1.8
    ) == pytest.approx(h2.percentile(0.5))
    # and tolerates an empty window
    assert percentile_from_buckets((1.0, 2.0), [0, 0, 0], 0.5) is None
