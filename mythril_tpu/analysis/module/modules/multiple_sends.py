"""MultipleSends: multiple external calls in a single transaction (SWC-113).

Reference parity: mythril/analysis/module/modules/multiple_sends.py:1-105.
"""

from __future__ import annotations

from typing import List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import MULTIPLE_SENDS
from mythril_tpu.core.state.annotation import StateAnnotation
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.exceptions import UnsatError

DESCRIPTION = "Check for multiple sends in a single transaction."


class MultipleSendsAnnotation(StateAnnotation):
    def __init__(self):
        self.call_offsets: List[int] = []

    def __copy__(self):
        out = MultipleSendsAnnotation()
        out.call_offsets = list(self.call_offsets)
        return out


class MultipleSends(DetectionModule):
    name = "Multiple external calls in the same transaction"
    swc_id = MULTIPLE_SENDS
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL", "DELEGATECALL", "STATICCALL", "CALLCODE", "RETURN", "STOP"]
    # staticpass: the RETURN/STOP hooks only report sends recorded by the
    # call hooks, so no call-family op means no possible issue
    static_required_ops = frozenset({"CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"})

    def _execute(self, state: GlobalState) -> Optional[List[Issue]]:
        if self._cache_key(state) in self.cache:
            return None
        return self._analyze_state(state)

    def _analyze_state(self, state: GlobalState) -> List[Issue]:
        annotations = state.get_annotations(MultipleSendsAnnotation)
        if not annotations:
            annotation = MultipleSendsAnnotation()
            state.annotate(annotation)
        else:
            annotation = annotations[0]

        opcode = state.get_current_instruction()["opcode"]
        if opcode in ("CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"):
            annotation.call_offsets.append(state.get_current_instruction()["address"])
            return []

        # RETURN / STOP
        if len(annotation.call_offsets) < 2:
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints.get_all_constraints()
            )
        except UnsatError:
            return []
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.node.function_name if state.node else "unknown",
                address=annotation.call_offsets[1],
                swc_id=MULTIPLE_SENDS,
                title="Multiple Calls in a Single Transaction",
                severity="Low",
                bytecode=state.environment.code.bytecode,
                description_head="Multiple calls are executed in the same transaction.",
                description_tail=(
                    "This call is executed following another call within the same "
                    "transaction. It is possible that the call never gets executed "
                    "if a prior call fails permanently. This might be caused "
                    "intentionally by a malicious callee. If possible, refactor "
                    "the code such that each transaction only executes one "
                    "external call or make sure that all callees can be trusted "
                    "(i.e. they're part of your own codebase)."
                ),
                gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
                transaction_sequence=transaction_sequence,
            )
        ]


detector = MultipleSends
