"""Horizontal worker pool: N analysis worker processes, one event plane.

The pool owns process lifecycle and nothing else — admission, flights,
telemetry and result caching stay in the daemon, which keeps the
admission plane thin (the EVMx host/accelerator split, applied to
serving).  Each worker gets a private job queue (so a job's owner is
always known, and a dead worker's in-flight loss is exactly its current
job); all workers share one event queue the pool's pump thread drains
into the daemon's callback.

Crash containment: the pump doubles as a liveness monitor.  A worker
process that dies without sending ``done`` (SIGKILL, OOM, segfault in a
native solver) is detected by ``Process.is_alive()``; the pool emits a
synthetic ``("worker_died", worker_id, job_id, pid)`` event — the daemon
errors only that job's requests and dumps a flight-recorder bundle — and
respawns a fresh worker process in its slot.  Nothing is silently
requeued: a lost request errors, visibly, exactly once.
"""

from __future__ import annotations

import itertools
import logging
import multiprocessing
import queue as queue_mod
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from mythril_tpu.service.worker import worker_main

log = logging.getLogger(__name__)

__all__ = ["WorkerHandle", "WorkerPool"]

# states a worker slot moves through
STARTING = "starting"
IDLE = "idle"
BUSY = "busy"
DEAD = "dead"
STOPPING = "stopping"


class WorkerHandle:
    """One worker slot: a process, its private job queue, and its state.

    The slot survives its process — ``respawn`` replaces a dead process
    in place, bumping ``restarts``, so worker ids are stable for
    telemetry (``myth top`` shows w0..wN-1 for the daemon's lifetime).
    """

    def __init__(self, worker_id: int, config: Dict[str, Any],
                 event_q, mp_ctx):
        self.id = worker_id
        self.config = config
        self.event_q = event_q
        self._mp = mp_ctx
        self.restarts = 0
        self.batches = 0
        self.state = DEAD
        self.current_job: Optional[int] = None
        self.proc = None
        self.job_q = None
        self.control_q = None
        self.started_at = 0.0

    def spawn(self) -> None:
        self.job_q = self._mp.Queue()
        # fresh control queue per process: a queue fed to a dead process
        # may hold a wedged feeder thread, and the respawned worker must
        # not replay the old process's control backlog
        self.control_q = self._mp.Queue()
        self.proc = self._mp.Process(
            target=worker_main,
            args=(self.id, self.config, self.job_q, self.event_q,
                  self.control_q),
            name=f"service-worker-{self.id}",
            daemon=True,
        )
        self.state = STARTING
        self.current_job = None
        self.started_at = time.time()
        self.proc.start()

    def respawn(self) -> None:
        self.restarts += 1
        self.spawn()

    def alive(self) -> bool:
        return self.proc is not None and self.proc.is_alive()

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def stats(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "pid": self.pid,
            "state": self.state,
            "job": self.current_job,
            "batches": self.batches,
            "restarts": self.restarts,
            "age_s": round(time.time() - self.started_at, 1)
            if self.started_at else 0.0,
        }


class WorkerPool:
    """N worker processes behind one pump thread.

    ``on_event`` is invoked in the pump thread for every worker event
    (after the pool updates slot state), including the synthetic
    ``worker_died``.  The callback must never raise for long — it owns
    flight fan-out, which is lock-bounded, not engine-bounded.
    """

    def __init__(self, n: int, config: Dict[str, Any],
                 on_event: Callable[[tuple], None]):
        if n < 1:
            raise ValueError("worker pool needs at least 1 worker")
        self._mp = multiprocessing.get_context("spawn")
        self.event_q = self._mp.Queue()
        self.on_event = on_event
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._stopping = False
        self._ready_once: set = set()
        self._all_ready = threading.Event()
        self.handles: List[WorkerHandle] = [
            WorkerHandle(i, config, self.event_q, self._mp) for i in range(n)
        ]
        self._job_ids = itertools.count(1)
        for h in self.handles:
            h.spawn()
        self._pump = threading.Thread(
            target=self._pump_loop, name="service-pool-pump", daemon=True
        )
        self._pump.start()

    # -- daemon side ---------------------------------------------------

    def new_job_id(self) -> int:
        return next(self._job_ids)

    def acquire(self, timeout: Optional[float] = None
                ) -> Optional[WorkerHandle]:
        """Block until a worker is idle; claim and return it (or None)."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        with self._idle:
            while True:
                if self._stopping:
                    return None
                for h in self.handles:
                    if h.state == IDLE:
                        h.state = BUSY
                        return h
                remaining = None if deadline is None \
                    else deadline - time.perf_counter()
                if remaining is not None and remaining <= 0:
                    return None
                self._idle.wait(timeout=remaining if remaining is not None
                                else 0.5)

    def release(self, handle: WorkerHandle) -> None:
        """Return a claimed-but-undispatched worker to the idle set."""
        with self._idle:
            if handle.state == BUSY and handle.current_job is None:
                handle.state = IDLE
                self._idle.notify_all()

    def dispatch(self, handle: WorkerHandle, job_id: int,
                 flights: List[Dict[str, Any]],
                 options: Dict[str, Any]) -> None:
        """Send one batch job to a claimed worker."""
        with self._lock:
            handle.current_job = job_id
            handle.batches += 1
        handle.job_q.put(("batch", job_id, flights, options))

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until every worker has reported ready at least once."""
        return self._all_ready.wait(timeout)

    def control(self, worker_id: int, msg: tuple) -> bool:
        """Send one control message to a live worker's control thread."""
        with self._lock:
            if not 0 <= worker_id < len(self.handles):
                return False
            h = self.handles[worker_id]
            if not h.alive() or h.control_q is None:
                return False
            q = h.control_q
        try:
            q.put(msg)
            return True
        except Exception:
            return False

    def broadcast_control(self, msg: tuple) -> List[int]:
        """Send a control message to every live worker; returns their ids."""
        reached = []
        for h in self.handles:
            if self.control(h.id, msg):
                reached.append(h.id)
        return reached

    def stats(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [h.stats() for h in self.handles]

    def depths(self) -> Dict[str, int]:
        """Heartbeat payload: worker-slot states at a glance."""
        with self._lock:
            states = [h.state for h in self.handles]
        return {
            "service.workers": len(states),
            "service.workers_idle": states.count(IDLE),
            "service.workers_busy": states.count(BUSY),
            "service.workers_starting": states.count(STARTING),
        }

    def total_restarts(self) -> int:
        with self._lock:
            return sum(h.restarts for h in self.handles)

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful stop: ask every worker to exit, then reap."""
        with self._idle:
            self._stopping = True
            self._idle.notify_all()
        for h in self.handles:
            if h.alive() and h.job_q is not None:
                try:
                    h.job_q.put(("stop",))
                except Exception:
                    pass
        deadline = time.perf_counter() + timeout
        for h in self.handles:
            if h.proc is None:
                continue
            h.proc.join(timeout=max(deadline - time.perf_counter(), 0.1))
            if h.proc.is_alive():
                log.warning("worker %d did not drain; terminating", h.id)
                h.proc.terminate()
                h.proc.join(timeout=5.0)
            h.state = DEAD
        self._pump.join(timeout=5.0)

    # -- pump thread ---------------------------------------------------

    def _pump_loop(self) -> None:
        """Drain worker events + watch liveness until stop completes."""
        while True:
            try:
                msg = self.event_q.get(timeout=0.2)
            except queue_mod.Empty:
                msg = None
            if msg is not None:
                self._handle_event(msg)
            self._check_liveness()
            if self._stopping and all(
                not h.alive() for h in self.handles
            ):
                return

    def _handle_event(self, msg: tuple) -> None:
        kind = msg[0]
        wid = msg[1]
        handle = self.handles[wid]
        if kind == "ready":
            with self._idle:
                handle.state = IDLE
                self._idle.notify_all()
            self._ready_once.add(wid)
            if len(self._ready_once) == len(self.handles):
                self._all_ready.set()
        elif kind == "done":
            job_id = msg[2]
            with self._idle:
                if handle.current_job == job_id:
                    handle.current_job = None
                    handle.state = IDLE if not self._stopping else STOPPING
                    self._idle.notify_all()
        elif kind == "stopped":
            with self._lock:
                handle.state = STOPPING
        try:
            self.on_event(msg)
        except Exception:
            log.exception("pool event callback failed for %r", kind)

    def _check_liveness(self) -> None:
        for h in self.handles:
            if h.state in (DEAD, STOPPING) or h.proc is None:
                continue
            if h.proc.is_alive():
                continue
            # a worker died without a terminal message
            with self._idle:
                lost_job, pid = h.current_job, h.pid
                h.current_job = None
                h.state = DEAD
            if self._stopping and lost_job is None:
                continue  # normal exit race during shutdown
            log.error("worker %d (pid %s) died%s", h.id, pid,
                      f" holding job {lost_job}" if lost_job else "")
            if not self._stopping:
                h.respawn()
                with self._idle:
                    self._idle.notify_all()
            try:
                self.on_event(("worker_died", h.id, lost_job, pid))
            except Exception:
                log.exception("pool worker_died callback failed")
