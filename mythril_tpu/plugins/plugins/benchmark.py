"""Benchmark plugin: coverage-over-time + executed-instruction counts.

Reference parity: mythril/laser/plugin/plugins/benchmark.py:19-94.  The
reference renders a matplotlib png at shutdown; this environment is headless
and matplotlib-free, so the series is persisted as JSON plus a
dependency-free SVG line chart (single series: executed instructions over
wall time).
"""

from __future__ import annotations

import json
import logging
import time
from typing import List, Tuple
from xml.sax.saxutils import escape

from mythril_tpu.plugins.interface import LaserPlugin, PluginBuilder

log = logging.getLogger(__name__)

# chart tokens (light surface), single categorical series
_SURFACE = "#fcfcfb"
_INK = "#0b0b0b"
_INK_MUTED = "#52514e"
_SERIES = "#2a78d6"
_GRID = "#e8e7e4"


def render_series_svg(
    points: List[Tuple[float, int]],
    title: str,
    y_label: str = "executed instructions",
    width: int = 640,
    height: int = 360,
) -> str:
    """A minimal single-series line chart as standalone SVG markup.

    One series needs no legend (the title names it); the line is 2px, the
    grid recessive, text in ink tokens rather than the series color.
    """
    ml, mr, mt, mb = 56, 16, 40, 36  # margins: left/right/top/bottom
    pw, ph = width - ml - mr, height - mt - mb
    title, y_label = escape(title), escape(y_label)
    xs = [p[0] for p in points] or [0.0]
    ys = [p[1] for p in points] or [0]
    x_max = max(xs) or 1.0
    y_max = max(ys) or 1

    def px(x: float) -> float:
        return ml + (x / x_max) * pw

    def py(y: float) -> float:
        return mt + ph - (y / y_max) * ph

    # ~4 horizontal gridlines at round y values
    step = max(1, y_max // 4)
    grid, labels = [], []
    y = step
    while y <= y_max:
        gy = py(y)
        grid.append(
            f'<line x1="{ml}" y1="{gy:.1f}" x2="{ml + pw}" y2="{gy:.1f}" '
            f'stroke="{_GRID}" stroke-width="1"/>'
        )
        labels.append(
            f'<text x="{ml - 6}" y="{gy + 4:.1f}" text-anchor="end" '
            f'font-size="11" fill="{_INK_MUTED}">{y}</text>'
        )
        y += step
    path = " ".join(
        f"{'M' if i == 0 else 'L'}{px(x):.1f},{py(v):.1f}"
        for i, (x, v) in enumerate(points or [(0.0, 0)])
    )
    last_x, last_y = points[-1] if points else (0.0, 0)
    font = "font-family='system-ui, sans-serif'"
    return (
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
        f'height="{height}" viewBox="0 0 {width} {height}">'
        f'<rect width="{width}" height="{height}" fill="{_SURFACE}"/>'
        f'<text x="{ml}" y="22" font-size="14" {font} fill="{_INK}">{title}</text>'
        + "".join(grid)
        + "".join(labels)
        + f'<line x1="{ml}" y1="{mt + ph}" x2="{ml + pw}" y2="{mt + ph}" '
        f'stroke="{_INK_MUTED}" stroke-width="1"/>'
        f'<path d="{path}" fill="none" stroke="{_SERIES}" stroke-width="2" '
        f'stroke-linejoin="round"><title>{y_label}</title></path>'
        f'<circle cx="{px(last_x):.1f}" cy="{py(last_y):.1f}" r="3" '
        f'fill="{_SERIES}"/>'
        f'<text x="{ml}" y="{height - 8}" font-size="11" {font} '
        f'fill="{_INK_MUTED}">0</text>'
        f'<text x="{ml + pw}" y="{height - 8}" text-anchor="end" font-size="11" '
        f'{font} fill="{_INK_MUTED}">{x_max:.1f}s</text>'
        "</svg>"
    )


class BenchmarkPlugin(LaserPlugin):
    def __init__(self, name: str = "benchmark"):
        self.nr_of_executed_insns = 0
        self.begin: float = 0.0
        self.end: float = 0.0
        self.points: List[Tuple[float, int]] = []
        self.name = name
        self._device_insns_at_start = 0

    def initialize(self, symbolic_vm) -> None:
        self.begin = time.perf_counter()
        # the series tracks host-stepped instructions (execute_state hooks);
        # device-frontier segments bypass those hooks, so their instruction
        # total is reported separately from FrontierStatistics
        from mythril_tpu.frontier.stats import FrontierStatistics

        self._device_insns_at_start = FrontierStatistics().device_instructions

        def execute_state_hook(_):
            self.nr_of_executed_insns += 1
            self.points.append((time.perf_counter() - self.begin, self.nr_of_executed_insns))

        def stop_hook():
            self.end = time.perf_counter()
            duration = self.end - self.begin
            rate = self.nr_of_executed_insns / duration if duration > 0 else 0.0
            log.info(
                "Benchmark: %d instructions in %.2fs (%.0f/s)",
                self.nr_of_executed_insns,
                duration,
                rate,
            )

        symbolic_vm.register_laser_hooks("execute_state", execute_state_hook)
        symbolic_vm.register_laser_hooks("stop_sym_exec", stop_hook)

    def write_to_file(self, path: str) -> None:
        """Persist the series as JSON at ``path`` and an SVG chart at
        ``path + ".svg"`` — the role of the reference's matplotlib png.

        Long runs are downsampled to <=10000 points spanning the WHOLE run
        (stride recorded in the JSON), never truncated."""
        from mythril_tpu.frontier.stats import FrontierStatistics

        stride = max(1, -(-len(self.points) // 10000))  # ceil div
        series = self.points[::stride]
        if series and self.points[-1] != series[-1]:
            series.append(self.points[-1])
        device_insns = (
            FrontierStatistics().device_instructions - self._device_insns_at_start
        )
        with open(path, "w") as f:
            json.dump(
                {
                    "executed_instructions": self.nr_of_executed_insns,
                    # instructions executed by device-frontier segments (not
                    # in the host hook series; 0 unless --frontier)
                    "device_instructions": device_insns,
                    "duration": self.end - self.begin,
                    "series_stride": stride,
                    "series": series,
                },
                f,
            )
        with open(path + ".svg", "w") as f:
            f.write(
                render_series_svg(
                    series, title=f"{self.name}: instructions over time"
                )
            )


class BenchmarkPluginBuilder(PluginBuilder):
    name = "benchmark"

    def __call__(self, *args, **kwargs) -> LaserPlugin:
        return BenchmarkPlugin()
