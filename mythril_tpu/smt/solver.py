"""Constraint solving without Z3: directed probing + (later) native CDCL.

The reference delegates every satisfiability question to Z3
(mythril/laser/smt/solver/solver.py:51-121, mythril/support/model.py:15-63).
No Z3 exists in this environment, so this framework carries its own stack:

  tier 0  eager constant folding (terms.py) — most queries collapse here;
  tier 1  directed probing: back-propagate ``X == const`` constraints through
          invertible ops into leaf bits (a constraint-directed fuzzer), then
          fill the rest with structured random candidates and evaluate the
          whole DAG exactly (host big-int path, or batched on TPU via
          mythril_tpu/ops/lowering.py when available).  A hit IS a model —
          probing is sound by construction;
  tier 2  native C++ bit-blasting CDCL (mythril_tpu/native/) for exact UNSAT
          and hard SAT instances.

SAT answers are always accompanied by a validated model.  UNSAT without the
native tier is heuristic ("no model found in budget"), which mirrors the
reference's behavior under ``--solver-timeout`` where unknown is treated as
unsat (mythril/support/model.py:60-63).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from mythril_tpu.exceptions import UnsatError
from mythril_tpu.observability import tracer as _otrace
from mythril_tpu.smt import terms
from mythril_tpu.smt.concrete_eval import ArrayValue, Assignment, evaluate
from mythril_tpu.smt.terms import Term, mask
from mythril_tpu.support.support_args import args as global_args

log = logging.getLogger(__name__)

SAT = "sat"
UNSAT = "unsat"
UNKNOWN = "unknown"


# ---------------------------------------------------------------------------
# Statistics (reference smt/solver/solver_statistics.py:29)
# ---------------------------------------------------------------------------


def _solver_counter_prop(attr: str, initial=0, doc: str = ""):
    name = "solver." + attr

    def fget(self):
        return _metrics_registry().counter(name, initial=initial).value

    def fset(self, v):
        _metrics_registry().counter(name, initial=initial).set(v)

    return property(fget, fset, doc=doc)


def _metrics_registry():
    from mythril_tpu.observability.metrics import get_registry

    return get_registry()


class SolverStatistics:
    """Process-wide counters for solver usage (singleton).

    Thin facade over the ``solver.*`` metrics in the observability
    registry: each attribute is a property over a named counter, so the
    ``stats.inc("query_count")`` call sites (and tests that assign
    directly) work unchanged while the numbers flow into
    ``--metrics-out`` / ``meta.observability`` snapshots.  ``enabled``
    is plain instance state, not telemetry, and survives resets.
    """

    _instance = None

    query_count = _solver_counter_prop("query_count")
    solver_time = _solver_counter_prop("solver_time_s", initial=0.0)
    probe_hits = _solver_counter_prop("probe_hits")
    cdcl_calls = _solver_counter_prop("cdcl_calls")
    # completeness boundary: prune decisions taken on an UNKNOWN
    # verdict (probe exhausted AND no exact CDCL answer) — every one
    # is a potential recall loss, so runs should see this at 0
    unknown_as_unsat = _solver_counter_prop("unknown_as_unsat")

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enabled = False
            cls._instance.reset()
        return cls._instance

    # attribute -> (registry counter name, zero value); ``inc`` goes
    # through ``Counter.inc`` (which holds the metrics mutation lock) so
    # increments from feasibility-pool worker threads are atomic —
    # ``stats.x += 1`` is a property get *then* set and can lose updates
    # under concurrency
    _counters = {
        "query_count": ("solver.query_count", 0),
        "solver_time": ("solver.solver_time_s", 0.0),
        "probe_hits": ("solver.probe_hits", 0),
        "cdcl_calls": ("solver.cdcl_calls", 0),
        "unknown_as_unsat": ("solver.unknown_as_unsat", 0),
    }

    def inc(self, attr: str, n=1) -> None:
        """Thread-safe ``attr += n`` (use instead of ``+=`` on solve paths)."""
        name, initial = self._counters[attr]
        _metrics_registry().counter(name, initial=initial).inc(n)

    def reset(self) -> None:
        """Zero the solver-scoped metrics (not the ``enabled`` switch)."""
        _metrics_registry().reset(prefix="solver.")
        # force-create the backing counters so snapshots always carry
        # the full solver block even before the first query
        _ = (self.query_count, self.solver_time, self.probe_hits,
             self.cdcl_calls, self.unknown_as_unsat)

    def __repr__(self):
        return (
            f"Solver statistics: query count: {self.query_count}, "
            f"solver time: {self.solver_time:.3f}, probe hits: {self.probe_hits}, "
            f"cdcl calls: {self.cdcl_calls}, "
            f"unknown treated as unsat: {self.unknown_as_unsat}"
        )


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------


class Model:
    """A satisfying assignment; eval() reifies any expression under it.

    Reference counterpart: mythril/laser/smt/model.py — but there is exactly
    one backing assignment here (no multi-model merging needed: the
    independence-split solver evaluates the joint assignment directly).
    """

    def __init__(self, assignment: Assignment):
        self.assignment = assignment

    def eval(self, expr, model_completion: bool = True):
        raw = expr.raw if hasattr(expr, "raw") else expr
        return evaluate([raw], self.assignment)[raw]

    def decls(self):
        return list(self.assignment.scalars.keys())


# ---------------------------------------------------------------------------
# Directed value propagation
# ---------------------------------------------------------------------------


class _PartialBits:
    """Per-variable partially-known bits (strong hints from equalities;
    the first directed hint for a bit wins)."""

    __slots__ = ("known", "value", "width")

    def __init__(self, width: int):
        self.known = 0
        self.value = 0
        self.width = width

    def set_bits(self, bitmask: int, bits: int) -> None:
        new = bitmask & ~self.known
        self.known |= new
        self.value |= bits & new

    def complete(self, fill: int) -> int:
        return (self.value & self.known) | (fill & ~self.known & ((1 << self.width) - 1))


def _clone_bits(h: "_PartialBits") -> "_PartialBits":
    out = _PartialBits(h.width)
    out.known, out.value = h.known, h.value
    return out


class _Seeder:
    """Collects directed hints from equality constraints and constant pools.

    Disjunctions wanted true are collected as *choice groups*: each probe
    candidate commits to one disjunct per group (rotating with the candidate
    index), so constraints like ``caller == A ∨ caller == B ∨ caller == C``
    or selector alternations are solved by construction, not by luck.
    """

    def __init__(self, conjuncts: Sequence[Term], collect_groups: bool = True):
        self.conjuncts = conjuncts
        self.scalar_hints: Dict[Term, _PartialBits] = {}
        self.bool_hints: Dict[Term, bool] = {}
        # (array_var term, concrete index) -> byte/word hints
        self.array_hints: Dict[Tuple[Term, int], int] = {}
        # selects at COMPUTED indices (ABI dynamic-array head indirection:
        # ``calldataload(4 + calldataload(4))``): (base array, index term,
        # value); installed at candidate-build time by evaluating the index
        # under the partial assignment (two passes = one indirection level)
        self.dyn_array_hints: List[Tuple[Term, Term, int]] = []
        # (base array, (lo, hi)) byte runs acting as data POINTERS inside a
        # dyn index term; unconstrained ones are pre-seeded past the hinted
        # head region so indirect writes never alias the pointer itself
        self.dyn_preseed: List[Tuple[Term, Tuple[int, int]]] = []
        self.const_pool: List[int] = []
        # weak full-variable hints (inequality boundaries): max-combined so
        # e.g. repeated ``i < calldatasize`` reads push the size upward
        self.weak_vals: Dict[Term, int] = {}
        # symbolic-symbolic equalities (e.g. caller == sload(owner_slot)):
        # resolved at assignment-build time by copying the evaluated side
        self.link_pairs: List[Tuple[Term, Term]] = []
        # symbolic-symbolic unsigned orderings (lo, hi, bump): lo + bump
        # must not exceed hi (e.g. callvalue <= balances[sender], the
        # balance-transfer constraint every message call carries); repaired
        # at build time by raising hi (preferred) or lowering lo
        self.order_pairs: List[Tuple[Term, Term, int]] = []
        # disequalities (a, b) wanted different (JUMPI taken branches are
        # Not(cond == 0)); repaired at build time by flipping the low bit
        # of one side through the invertible-op machinery
        self.neq_pairs: List[Tuple[Term, Term]] = []
        self.or_groups: List[List[Term]] = []
        self._overlay_cache: Dict[tuple, "_Seeder"] = {}
        self._collect_groups = collect_groups
        self._harvest()
        self._propagate_all()
        self._analyze_dyn_hints()

    def overlay_for(self, candidate_index: int) -> "_Seeder":
        """Base hints + one committed disjunct per or-group.

        Disjunct combinations are enumerated mixed-radix over the candidate
        index so every combination is eventually committed, and overlays are
        memoized per combination (only prod(len(g)) distinct ones exist).
        """
        if not self.or_groups:
            return self
        choices = []
        div = 1
        for group in self.or_groups:
            choices.append((candidate_index // div) % len(group))
            div *= len(group)
        key = tuple(choices)
        cached = self._overlay_cache.get(key)
        if cached is not None:
            return cached
        clone = _Seeder.__new__(_Seeder)
        clone.conjuncts = self.conjuncts
        clone.scalar_hints = {
            t: _clone_bits(h) for t, h in self.scalar_hints.items()
        }
        clone.bool_hints = dict(self.bool_hints)
        clone.array_hints = dict(self.array_hints)
        clone.weak_vals = dict(self.weak_vals)
        clone.dyn_array_hints = list(self.dyn_array_hints)
        clone.dyn_preseed = list(self.dyn_preseed)
        clone.link_pairs = list(self.link_pairs)
        clone.order_pairs = list(self.order_pairs)
        clone.neq_pairs = list(self.neq_pairs)
        clone.const_pool = self.const_pool
        clone.or_groups = []
        clone._collect_groups = False
        clone._overlay_cache = {}
        for gi, group in enumerate(self.or_groups):
            clone._propagate_bool(group[choices[gi]], True)
        self._overlay_cache[key] = clone
        return clone

    # -- constant pool: every literal in the DAG is an interesting value
    def _harvest(self):
        pool = set()
        for t in terms.topo_order(self.conjuncts):
            if t.op == "const" and t.sort is not terms.BOOL:
                v = t.aux
                for cand in (v, v - 1, v + 1, (1 << t.sort[1]) - v if v else 0):
                    pool.add(mask(cand, 256))
        pool |= {0, 1, 2, (1 << 256) - 1, (1 << 255), (1 << 160) - 1}
        self.const_pool = sorted(pool)

    def _hint(self, t: Term) -> _PartialBits:
        h = self.scalar_hints.get(t)
        if h is None:
            h = _PartialBits(t.width)
            self.scalar_hints[t] = h
        return h

    def _propagate_all(self):
        for c in self.conjuncts:
            self._propagate_bool(c, True)

    def _propagate_bool(self, t: Term, want: bool):
        if t.op == "var" and t.sort is terms.BOOL:
            self.bool_hints.setdefault(t, want)
            return
        if t.op == "and" and want:
            for a in t.args:
                self._propagate_bool(a, True)
            return
        if t.op == "or" and not want:
            for a in t.args:
                self._propagate_bool(a, False)
            return
        if t.op == "or" and want:
            if self._collect_groups:
                self.or_groups.append(list(t.args))
            else:
                self._propagate_bool(t.args[0], True)
            return
        if t.op == "not":
            self._propagate_bool(t.args[0], not want)
            return
        if t.op == "ite":
            # make the condition pick the branch that can satisfy `want`
            c, a, b = t.args
            if a.op == "const" and bool(a.aux) == want:
                self._propagate_bool(c, True)
                return
            if b.op == "const" and bool(b.aux) == want:
                self._propagate_bool(c, False)
                return
            return
        if t.op == "eq":
            a, b = t.args
            if not terms.is_bv_sort(a.sort):
                return
            if want:
                if a.is_const:
                    self._propagate_value(b, a.value)
                elif b.is_const:
                    self._propagate_value(a, b.value)
                else:
                    self.link_pairs.append((a, b))
            elif not (a.is_const and b.is_const):
                self.neq_pairs.append((a, b))
            return
        # Inequalities: lower bounds push the variable just past the bound;
        # upper bounds hint zero (weak hints max-combine, so lower bounds win
        # over the zero default and minimization-style caps stay harmless).
        if t.op in ("ult", "ule", "slt", "sle"):
            a, b = t.args
            if not want and t.op in ("ult", "ule"):
                # Not(a < b) == b <= a; Not(a <= b) == b < a
                bump = 1 if t.op == "ule" else 0
                if not (a.is_const and b.is_const):
                    self.order_pairs.append((b, a, bump))
                return
            if want and a.is_const and not b.is_const:
                # strict bounds need bound+1; non-strict are satisfied at the
                # bound itself (and must not wrap for an all-ones bound)
                bump = 1 if t.op in ("ult", "slt") else 0
                self._propagate_value(b, mask(a.value + bump, b.width), weak=True)
                if t.op in ("ult", "ule"):
                    # repairable at build time too: the weak hint dies inside
                    # non-invertible ops (``2^w <= mul(...)`` overflow bounds)
                    self.order_pairs.append((a, b, bump))
            elif want and not a.is_const:
                if b.is_const:
                    self._propagate_value(a, 0, weak=True)
                elif t.op in ("ult", "ule"):
                    # both sides symbolic: repairable ordering at build time.
                    # Plain VARIABLES on the low side keep the weak zero
                    # seed (call_value <= balance-chain constraints repair
                    # trivially at zero); computed terms do not — a zero
                    # hint through an ``idx < size`` bounds guard poisons
                    # the read index the repair satisfies by raising
                    # ``size`` instead.
                    if a.op == "var":
                        self._propagate_value(a, 0, weak=True)
                    self.order_pairs.append((a, b, 1 if t.op == "ult" else 0))
                else:
                    # signed orderings have no repair machinery: keep the
                    # weak zero seed as candidate guidance
                    self._propagate_value(a, 0, weak=True)

    def _analyze_dyn_hints(self) -> None:
        """Find pointer words inside computed-select index terms.

        A dyn index like ``bvadd(calldataload(4), 4+j)`` embeds const-index
        selects over the SAME array (the ABI head word holding the data
        offset).  Maximal runs of consecutive const indices are recorded as
        pointer words so candidate construction can pre-seed unconstrained
        ones to a canonical non-aliasing offset (solc would emit 0x20)."""
        if not self.dyn_array_hints:
            return
        seen_idx = set()
        seen_runs = set()
        for base, idx, _ in self.dyn_array_hints:
            if idx.tid in seen_idx:
                continue
            seen_idx.add(idx.tid)
            const_reads = set()
            for t in terms.topo_order([idx]):
                if t.op == "select" and t.args[1].is_const:
                    b = t.args[0]
                    while b.op == "store":
                        b = b.args[0]
                    if b is base:
                        const_reads.add(t.args[1].value)
            if not const_reads:
                continue
            ordered = sorted(const_reads)
            start = prev = ordered[0]
            runs = []
            for v in ordered[1:]:
                if v == prev + 1:
                    prev = v
                    continue
                runs.append((start, prev))
                start = prev = v
            runs.append((start, prev))
            for run in runs:
                key = (base.tid, run)
                if key not in seen_runs:
                    seen_runs.add(key)
                    self.dyn_preseed.append((base, run))

    def _propagate_value(self, t: Term, value: int, weak: bool = False):
        """Push ``t == value`` down into leaves where ops are invertible."""
        width = t.width if terms.is_bv_sort(t.sort) else 1
        self._propagate_bits(t, mask(value, width), (1 << width) - 1, weak)

    def _propagate_bits(self, t: Term, value: int, claim: int, weak: bool):
        """Propagate ``t & claim == value & claim`` — only bits set in
        ``claim`` are actually constrained.  Shifts/masks narrow the claim
        instead of fabricating zero bits (a full-width claim through
        ``lshr(x, 224) == selector`` would wrongly pin the low 224 bits)."""
        if claim == 0:
            return
        full = (1 << t.width) - 1 if terms.is_bv_sort(t.sort) else 1
        claim &= full
        value &= claim
        if t.op == "var":
            if weak:
                if claim == full:
                    self.weak_vals[t] = max(self.weak_vals.get(t, 0), value)
            else:
                self._hint(t).set_bits(claim, value)
            return
        if t.op == "select":
            arr, idx = t.args
            base = arr
            while base.op == "store":
                base = base.args[0]
            if base.op == "array_var":
                if idx.is_const:
                    # partial claims (e.g. a bit test through a mask) still
                    # make a useful hint: unclaimed bits default to zero
                    self.array_hints.setdefault((base, idx.value), value)
                else:
                    # computed index (Z3 array-theory territory, reference
                    # mythril/laser/smt/array.py:45-72): resolved against
                    # the partial assignment at candidate-build time
                    self.dyn_array_hints.append((base, idx, value))
            return
        if t.op == "ite":
            # steer toward the then-branch (calldata/memory models guard
            # every byte with a bounds check, ite(i < size, select, 0)) —
            # EXCEPT for WEAK zero propagation that the else-branch already
            # supplies (a zero byte behind an OOB guard): forcing such a
            # guard true would drag its bound (calldatasize) past explicit
            # caps like ``calldatasize <= 0x25``.  Strong claims keep full
            # steering: a selector equality's zero high bits legitimately
            # pin bytes AND their in-range guards.
            c, a, b = t.args
            if weak and b.is_const and (b.value & claim) == value:
                return
            self._propagate_bool(c, True)
            self._propagate_bits(a, value, claim, weak)
            return
        if t.op == "bvand":
            a, b = t.args
            for cst, other in ((a, b), (b, a)):
                if cst.is_const:
                    if value & ~cst.aux & claim:
                        return  # needs a 1 where the mask forces 0
                    self._propagate_bits(other, value, claim & cst.aux, weak)
                    return
            return
        if t.op == "bvor":
            a, b = t.args
            for cst, other in ((a, b), (b, a)):
                if cst.is_const:
                    if (value ^ cst.aux) & cst.aux & claim:
                        return  # needs a 0 where the mask forces 1
                    self._propagate_bits(other, value, claim & ~cst.aux, weak)
                    return
            return
        if t.op == "concat":
            hi, lo = t.args
            lw = lo.width
            self._propagate_bits(lo, value, claim, weak)
            self._propagate_bits(hi, value >> lw, claim >> lw, weak)
            return
        if t.op == "extract":
            hi_bit, lo_bit = t.aux
            self._propagate_bits(t.args[0], value << lo_bit, claim << lo_bit, weak)
            return
        if t.op == "zext":
            inner = t.args[0]
            iw = (1 << inner.width) - 1
            if value & ~iw:
                return  # impossible: high bits nonzero
            self._propagate_bits(inner, value, claim & iw, weak)
            return
        if t.op == "sext":
            inner = t.args[0]
            iw = (1 << inner.width) - 1
            self._propagate_bits(inner, value & iw, claim & iw, weak)
            return
        if t.op == "bvxor":
            a, b = t.args
            for c, x in ((a, b), (b, a)):
                if c.is_const:
                    self._propagate_bits(x, value ^ (c.value & claim), claim, weak)
                    return
            return
        if t.op == "bvnot":
            self._propagate_bits(t.args[0], ~value & claim, claim, weak)
            return
        if t.op == "bvshl":
            a, b = t.args
            if b.is_const:
                k = min(b.value, t.width)
                self._propagate_bits(a, value >> k, (claim >> k) & full, weak)
            return
        if t.op == "bvlshr":
            a, b = t.args
            if b.is_const:
                k = min(b.value, t.width)
                self._propagate_bits(a, (value << k) & full, (claim << k) & full, weak)
            return
        # arithmetic inversions are only exact on a full claim
        if claim != full:
            return
        if t.op == "bvadd":
            a, b = t.args
            if a.is_const:
                self._propagate_bits(b, mask(value - a.value, t.width), full, weak)
            elif b.is_const:
                self._propagate_bits(a, mask(value - b.value, t.width), full, weak)
            return
        if t.op == "bvsub":
            a, b = t.args
            if b.is_const:
                self._propagate_bits(a, mask(value + b.value, t.width), full, weak)
            elif a.is_const:
                self._propagate_bits(b, mask(a.value - value, t.width), full, weak)
            return
        if t.op == "bvmul":
            a, b = t.args
            for c, x in ((a, b), (b, a)):
                if c.is_const and c.value % 2 == 1:
                    inv = pow(c.value, -1, 1 << t.width)
                    self._propagate_bits(x, mask(value * inv, t.width), full, weak)
                    return
            return
        if t.op == "ite":
            # try to make the then-branch produce the value
            c, a, b = t.args
            self._propagate_bool(c, True)
            self._propagate_bits(a, value, claim, weak=True)
            return


# ---------------------------------------------------------------------------
# The probe solver
# ---------------------------------------------------------------------------


def _device_backend_requested() -> bool:
    """Whether candidate evaluation may run through the device path at all.

    ``args.probe_backend``: "host" never, "jax" always, "auto" only when the
    process is already pointed at an accelerator platform (checked via env so
    the decision itself never triggers backend/tunnel initialization).
    """
    backend = getattr(global_args, "probe_backend", "auto")
    if backend == "host":
        return False
    if backend == "jax":
        return True
    platforms = os.environ.get("JAX_PLATFORMS", "")
    return platforms.startswith(("tpu", "axon"))


_topo_size_cache: Dict[frozenset, int] = {}


def _device_worthwhile(conjuncts: Sequence[Term], n_candidates: int) -> bool:
    """Latency-aware dispatch decision for the "auto" backend.

    A device dispatch costs a fixed round trip (milliseconds locally, ~100ms
    over a tunnel); the host evaluator costs ~DAG-size x candidates Python
    ops.  Small queries are faster on host, large batches over big DAGs on
    device — "auto" takes whichever side of the break-even the query lands
    on ("jax" always dispatches, which is what the raw-device benchmark
    measures).  Threshold tunable via ``args.device_probe_threshold``.
    """
    backend = getattr(global_args, "probe_backend", "auto")
    if backend == "jax":
        return True
    from mythril_tpu.support.calibration import calibrate

    calibrate()  # scale the threshold to the measured link (memoized)
    key = frozenset(c.tid for c in conjuncts)
    size = _topo_size_cache.get(key)
    if size is None:
        size = len(terms.topo_order(list(conjuncts)))
        if len(_topo_size_cache) > 8192:
            _topo_size_cache.clear()
        _topo_size_cache[key] = size
    threshold = getattr(global_args, "device_probe_threshold", 150_000)
    return size * max(1, n_candidates) >= threshold


def _evaluate_candidates_device(compiled, candidates):
    """One dispatch over all candidates; mesh-sharded when devices allow.

    With >1 attached device the per-conjunction compiled path spreads the
    candidate batch over the full frontier mesh (mythril_tpu/parallel); the
    tape VM ships fixed-bucket shapes so its single dispatch is already the
    production path on one chip.
    """
    import jax

    from mythril_tpu.ops.tape_vm import TapeCompiled

    if (
        not isinstance(compiled, TapeCompiled)
        and jax.device_count() > 1
        and len(candidates) >= 16
    ):
        from mythril_tpu.parallel import evaluate_batch_sharded

        return evaluate_batch_sharded(compiled, candidates)
    return compiled.evaluate_batch(candidates)


def _try_compile_device(conjuncts: Sequence[Term]):
    """Compile for batched device evaluation, or None (host handles all).

    The tape VM is the primary path: the interpreter program is compiled
    once per shape bucket, so a fresh conjunction costs only tensor packing.
    DAGs it cannot express fall back to the per-conjunction lowering (its
    own XLA compile per distinct conjunction — the expensive legacy path),
    and anything else falls through to the host evaluator.
    """
    try:
        from mythril_tpu.ops import lowering, tape_vm

        if getattr(global_args, "probe_backend", "auto") != "jax":
            # auto: never BLOCK a query on the one-time interpreter compile —
            # kick it in the background and stay on the host path until ready
            if not tape_vm.interpreter_ready():
                tape_vm.ensure_warming()
                return None
        try:
            return tape_vm.compile_tape(conjuncts)
        except tape_vm.TapeUnsupported as e:
            log.debug("tape VM unsupported (%s); per-conjunction fallback", e)
        return lowering.compile_cached(conjuncts)
    except Exception as e:
        log.debug("device lowering unavailable for query (%s): %s", type(e).__name__, e)
        return None


class ProbeConfig:
    def __init__(
        self,
        max_rounds: int = 4,
        candidates_per_round: int = 48,
        timeout_ms: int = 10_000,
        rng_seed: int = 0x5EED,
        prune_critical: bool = False,
        sat_biased: bool = False,
    ):
        self.max_rounds = max_rounds
        self.candidates_per_round = candidates_per_round
        self.timeout_ms = timeout_ms
        self.rng_seed = rng_seed
        # sat-biased queries (successor pruning, mutation-pruner sweeps) are
        # overwhelmingly satisfiable: a handful of directed candidates is
        # tried BEFORE the exact-UNSAT interval tier and the independence
        # split, so the common SAT answer skips their per-query DAG walks
        self.sat_biased = sat_biased
        # prune-critical queries (is_possible, frontier/batch pruning) kill
        # paths on UNSAT: the exact CDCL tier is guaranteed a time slice even
        # when the probe burned the whole deadline, so an UNKNOWN-driven
        # prune only happens when the exact tier genuinely ran out of road
        self.prune_critical = prune_critical


class CandidateGenerator:
    """Directed candidate construction for one conjunction.

    Wraps the _Seeder hint machinery (constant pools, bit hints, or-group
    overlays, symbolic-equality links) behind a simple ``generate(n)`` so
    both the single-query probe (solve_conjunction) and the frontier-batched
    prune (check_satisfiable_batch) build candidates the same way.
    """

    def __init__(self, conjuncts: Sequence[Term], config: "ProbeConfig"):
        self.conjuncts = list(conjuncts)
        free = terms.free_vars(self.conjuncts)
        self.scalar_vars = [v for v in free if v.op == "var"]
        self.array_vars = [v for v in free if v.op == "array_var"]
        self.seeder = _Seeder(self.conjuncts)
        self.rng = random.Random(config.rng_seed)
        self._fill_iter = _interesting_fills(
            self.rng, self.seeder.const_pool, 256
        )
        self._index = 0

    def generate(
        self, n: int, deadline: Optional[float] = None
    ) -> List[Assignment]:
        out = []
        for _ in range(n):
            if out and deadline is not None and time.perf_counter() > deadline:
                break
            out.append(self._build(self._index))
            self._index += 1
        return out

    def _build(self, candidate_index: int) -> Assignment:
        s = self.seeder.overlay_for(candidate_index)
        rng = self.rng
        use_weak = candidate_index % 3 != 2  # periodically explore past weak hints
        asg = Assignment()
        for v in self.scalar_vars:
            if v.sort is terms.BOOL:
                asg.scalars[v] = s.bool_hints.get(v, rng.random() < 0.5)
                continue
            hint = s.scalar_hints.get(v)
            if use_weak and v in s.weak_vals and (hint is None or hint.known == 0):
                fill = s.weak_vals[v]
            else:
                fill = next(self._fill_iter)
            if hint is not None:
                asg.scalars[v] = hint.complete(mask(fill, v.width))
            else:
                asg.scalars[v] = mask(fill, v.width)
        # every third candidate salts unhinted array reads: zero defaults
        # collapse distinct symbolic reads onto one value (array elements
        # hashing to the SAME storage slot), hiding distinctness models.
        # The salted SUBSET rotates per candidate — salting calldata makes
        # receiver keys distinct, while storage usually must keep its
        # zero default (fresh balances) for the same model to validate.
        salt_base = candidate_index + 1 if candidate_index % 3 == 1 else 0
        for k, av in enumerate(self.array_vars):
            backing = {
                idx: val for (a, idx), val in s.array_hints.items() if a is av
            }
            range_bits = av.sort[2] if len(av.sort) > 2 else 0
            salted = (
                salt_base
                if salt_base and ((candidate_index >> (k % 6)) & 1)
                else 0
            )
            asg.arrays[av] = ArrayValue(
                backing, default=0, salt=salted, range_bits=range_bits
            )
        self._apply_links(s, asg)
        self._apply_neq_pairs(s, asg)
        self._preseed_pointers(s, asg)
        self._apply_order_pairs(s, asg)
        self._apply_dyn_hints(s, asg)
        if s.dyn_array_hints:
            # indirect writes move evaluated indices (size guards, balance
            # orderings): repair orderings once more against the final state
            self._apply_order_pairs(s, asg)
        return asg

    @staticmethod
    def _preseed_pointers(s, asg: Assignment) -> None:
        """Give unconstrained pointer words a canonical non-aliasing value.

        For every pointer run found by ``_Seeder._analyze_dyn_hints``: if no
        byte of the run carries a hint or backing yet, write the first
        32-aligned offset past every hinted byte (big-endian into the run).
        This is the ABI-canonical shape — the dynamic data region starts
        after the argument head — and keeps the indirect write from landing
        on the pointer itself (off=0 would alias ``cnt`` with ``off``)."""
        if not s.dyn_preseed:
            return
        hi_water_by_arr: Dict[int, int] = {}
        for (arr, k) in s.array_hints:
            tid = arr.tid
            hi_water_by_arr[tid] = max(hi_water_by_arr.get(tid, 0), k)
        for base, (lo, hi) in s.dyn_preseed:
            backing = asg.arrays.setdefault(base, ArrayValue()).backing
            if any((base, k) in s.array_hints for k in range(lo, hi + 1)):
                continue
            if any(k in backing for k in range(lo, hi + 1)):
                continue  # link/force-written bytes (even zeros) are pinned
            hi_water = max(hi_water_by_arr.get(base.tid, 0), hi)
            ptr = ((hi_water + 32) // 32) * 32
            nbytes = hi - lo + 1
            if ptr.bit_length() > 8 * nbytes:
                continue
            for i, byte in enumerate(int(ptr).to_bytes(nbytes, "big")):
                backing.setdefault(lo + i, byte)

    @staticmethod
    def _apply_dyn_hints(s, asg: Assignment) -> None:
        """Install computed-index select hints (one indirection level).

        Each pass evaluates every index term under the current assignment
        and writes the hinted value at the resolved index (first write
        wins).  Two passes: pass one may move an index term's own inputs
        (e.g. writing the array length that a later read's index depends
        on), pass two lands the dependent hints."""
        if not s.dyn_array_hints:
            return
        idx_terms = [idx for _, idx, _ in s.dyn_array_hints]
        for _ in range(2):
            try:
                vals = evaluate(idx_terms, asg)
            except NotImplementedError:
                return
            changed = False
            for arr, idx, value in s.dyn_array_hints:
                backing = asg.arrays.setdefault(arr, ArrayValue()).backing
                iv = vals[idx]
                if iv not in backing:
                    backing[iv] = value
                    changed = True
            if not changed:
                return

    def _apply_neq_pairs(self, s, asg: Assignment) -> None:
        """Repair violated disequalities by flipping the low bit of one side
        through the invertible-op machinery (a != b is almost always a taken
        JUMPI branch, Not(cond == 0)).  All sides evaluate in ONE DAG walk —
        per-pair walks dominated candidate-build time on wide frontiers."""
        if not s.neq_pairs:
            return
        sides = [t for pair in s.neq_pairs for t in pair]
        try:
            vals = evaluate(sides, asg)
        except NotImplementedError:
            return
        for a, b in s.neq_pairs:
            if vals[a] != vals[b]:
                continue
            target = b if a.is_const else a
            self._force_value(target, mask(vals[target] ^ 1, target.width), asg)

    @staticmethod
    def _force_value(expr, desired: int, asg: Assignment) -> None:
        """Best-effort: drive ``expr`` toward ``desired`` by writing the
        scalar/array leaves the invertible-op propagation reaches."""
        tmp = _Seeder((), collect_groups=False)  # empty: a bare collector
        tmp._propagate_value(expr, desired)
        for v, hint in tmp.scalar_hints.items():
            if hint.known:
                asg.scalars[v] = hint.complete(asg.scalars.get(v, 0) or 0)
        for (arr, idx), val in tmp.array_hints.items():
            asg.arrays.setdefault(arr, ArrayValue()).backing[idx] = val
        if tmp.dyn_array_hints:
            idx_terms = [idx for _, idx, _ in tmp.dyn_array_hints]
            try:
                vals = evaluate(idx_terms, asg)
            except NotImplementedError:
                vals = None
            if vals is not None:
                for arr, idx, val in tmp.dyn_array_hints:
                    asg.arrays.setdefault(arr, ArrayValue()).backing[
                        vals[idx]
                    ] = val
        for v, bound in tmp.weak_vals.items():
            cur = asg.scalars.get(v, 0)
            if isinstance(cur, int) and cur < bound:
                asg.scalars[v] = bound

    @staticmethod
    def _link_target(t):
        """(kind, ...) if ``t`` is directly assignable in a candidate."""
        if t.op == "var" and t.sort is not terms.BOOL:
            return ("var", t)
        if t.op == "select" and t.args[0].op == "array_var" and t.args[1].is_const:
            return ("sel", t.args[0], t.args[1].value)
        return None

    @staticmethod
    def _dyn_target(t):
        """Like _link_target but also accepts a select whose key is any
        evaluable term (resolved against the assignment at write time) —
        e.g. ``balances[sender]`` with a symbolic sender."""
        info = CandidateGenerator._link_target(t)
        if info is not None:
            return info
        if t.op == "select" and t.args[0].op == "array_var":
            return ("dynsel", t.args[0], t.args[1])
        return None

    def _apply_order_pairs(self, s, asg: Assignment) -> None:
        """Repair violated symbolic orderings (lo + bump <= hi) by raising
        the upper side — writing through a var or an array cell whose key
        evaluates under the assignment — else lowering the lower side."""
        if not s.order_pairs:
            return
        sides = [t for lo, hi, _ in s.order_pairs for t in (lo, hi)]
        try:
            vals = evaluate(sides, asg)
        except NotImplementedError:
            return
        for lo, hi, bump in s.order_pairs:
            lo_v, hi_v = vals[lo], vals[hi]
            if lo_v + bump <= hi_v:
                continue
            hi_max = (1 << hi.width) - 1
            target = self._dyn_target(hi)
            if target is not None and lo_v + bump <= hi_max:
                self._dyn_write(target, lo_v + bump, asg, raise_only=True)
                continue
            if (
                hi.op == "bvmul"
                and lo_v + bump <= hi_max
                and self._raise_product(hi, lo_v + bump, asg)
            ):
                # product bound (overflow predicates: Not(BVMulNoOverflow)
                # is ``2^w <= mul(zext a, zext b)``): raise one FACTOR so
                # the product clears the bound — exact host arithmetic,
                # where the bit-blasted 2w-bit multiply is hopeless
                continue
            target = self._dyn_target(lo)
            if target is not None and hi_v >= bump:
                self._dyn_write(target, hi_v - bump, asg)

    def _raise_product(self, mul_term, target: int, asg: Assignment) -> bool:
        """Drive ``mul(x, y) >= target`` by forcing one factor to
        ceil(target / other) through the invertible-op write machinery.
        The side is randomized across candidates so a factor pinned by
        other constraints (a loop count with ``cnt <= 20``) gets the small
        role in half the attempts.  Returns False when nothing was written
        (caller falls back to lowering the other side of the pair)."""
        factors = [
            a.args[0] if a.op in ("zext", "sext") else a
            for a in mul_term.args[:2]
        ]
        try:
            vals = evaluate(factors, asg)
        except NotImplementedError:
            return False
        x, y = factors
        if self.rng.random() < 0.5:
            x, y = y, x
        base = vals[y]
        # the bound may exceed what x alone can supply (both factors at 1
        # for a 2^w overflow target): bump y to the SMALLEST value whose
        # cofactor fits in x — e.g. cnt=2, value=2^(w-1), respecting a tight
        # range constraint on y that a blunt 2^(w/2) split would violate
        min_base = -(-target // ((1 << x.width) - 1))
        if base < min_base:
            if min_base.bit_length() > y.width:
                return False
            self._force_value(y, min_base, asg)
            base = min_base
        need = -(-target // base)  # ceil
        if need.bit_length() > x.width:
            return False
        self._force_value(x, need, asg)
        return True

    @staticmethod
    def _dyn_write(
        info, value: int, asg: Assignment, raise_only: bool = False
    ) -> None:
        """``raise_only``: keep a larger already-written value (a batch of
        ``idx < size`` guards repaired in one sweep must leave ``size``
        above the LARGEST index, not whichever pair happened to come last)."""
        if info[0] == "var":
            cur = asg.scalars.get(info[1])
            if raise_only and isinstance(cur, int) and cur >= value:
                return
            asg.scalars[info[1]] = value
        elif info[0] == "sel":
            backing = asg.arrays.setdefault(info[1], ArrayValue()).backing
            cur = backing.get(info[2])
            if raise_only and isinstance(cur, int) and cur >= value:
                return
            backing[info[2]] = value
        else:  # dynsel: resolve the key against the current assignment
            try:
                key_v = evaluate([info[2]], asg)[info[2]]
            except NotImplementedError:
                return
            backing = asg.arrays.setdefault(info[1], ArrayValue()).backing
            cur = backing.get(key_v)
            if raise_only and isinstance(cur, int) and cur >= value:
                return
            backing[key_v] = value

    def _apply_links(self, s, asg: Assignment) -> None:
        """Copy evaluated values across symbolic equalities (two passes).

        Direction-aware: the determined side (strong hint, array hint, or a
        value written by an earlier link) is the source; the undetermined
        side is the target.  Both-determined pairs are left alone so
        constant-derived hints are never clobbered.
        """
        if not s.link_pairs:
            return
        written: set = set()
        link_target = self._link_target

        def determined(t) -> Optional[tuple]:
            info = link_target(t)
            if info is None:
                return ("expr",)  # complex expression: can only be a source
            if info[0] == "var":
                hint = s.scalar_hints.get(info[1])
                if (hint is not None and hint.known) or info[1] in written:
                    return ("set",)
                return None
            key = (info[1], info[2])
            if key in s.array_hints or key in written:
                return ("set",)
            return None

        def write(target, value) -> None:
            info = link_target(target)
            if info[0] == "var":
                asg.scalars[info[1]] = value
                written.add(info[1])
            else:
                asg.arrays.setdefault(info[1], ArrayValue()).backing[info[2]] = value
                written.add((info[1], info[2]))

        for _ in range(2):
            for a, b in s.link_pairs:
                da, db = determined(a), determined(b)
                if da is not None and db is None:
                    target, source = b, a
                elif db is not None and da is None:
                    target, source = a, b
                elif da is None and db is None:
                    target, source = a, b  # arbitrary: propagate left from right
                else:
                    continue  # both determined (or both unassignable)
                try:
                    value = evaluate([source], asg)[source]
                except NotImplementedError:
                    continue
                write(target, value)


def _interesting_fills(rng: random.Random, pool: Sequence[int], width: int):
    """Yield an endless stream of fill values for unknown bits."""
    yield 0
    yield (1 << width) - 1
    for v in pool:
        yield v
    while True:
        choice = rng.random()
        if choice < 0.35 and pool:
            yield rng.choice(pool)
        elif choice < 0.55:
            yield rng.getrandbits(8)
        elif choice < 0.75:
            # sparse random: few set bytes
            v = 0
            for _ in range(rng.randint(1, 4)):
                v |= rng.getrandbits(8) << (8 * rng.randint(0, max(0, width // 8 - 1)))
            yield v
        else:
            yield rng.getrandbits(width)


def independence_split(conjuncts: Sequence[Term]) -> List[List[Term]]:
    """Partition a conjunction into variable-independent buckets.

    Reference parity: the IndependenceSolver's shared-variable union-find
    (mythril/laser/smt/solver/independence_solver.py:38-83).  Buckets share
    no free variables, so they are solved separately and their models merged
    — each bucket is a smaller probe/CDCL instance, and per-bucket memoization
    means an engine query that extends one bucket leaves every other bucket's
    cached verdict intact.  Deterministic: buckets ordered by first conjunct.

    Memoized per conjunct set: a wide frontier poses hundreds of sibling
    queries per harvest and the union-find over the shared DAG was measured
    at ~20% of their solve time.
    """
    conjuncts = list(conjuncts)
    memo_key = frozenset(t.tid for t in conjuncts)
    hit = _split_cache.get(memo_key)
    if hit is not None:
        return hit
    # union-find over CONJUNCT indices
    parent = list(range(len(conjuncts)))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a: int, b: int) -> None:
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    # ONE global pass over the shared DAG: compute per-node "contains a free
    # variable", and reject uninterpreted functions — they couple buckets
    # through congruence even without shared variables (two buckets may
    # assign f the same input different outputs).  keccak is safe: it
    # evaluates concretely, so per-bucket models are globally consistent.
    dag = terms.topo_order(conjuncts)
    has_var: Dict[int, bool] = {}
    for t in dag:
        if t.op == "apply":
            _split_remember(memo_key, [conjuncts])
            return [conjuncts]
        has_var[t.tid] = t.op in ("var", "array_var") or any(
            has_var[a.tid] for a in t.args
        )

    # ONE ownership sweep: each variable-bearing node is claimed by the
    # first conjunct to reach it; later conjuncts stop at claimed nodes and
    # union with the owner, so every node is descended into at most once
    # across ALL conjuncts (shared path prefixes are not re-traversed).
    owner: Dict[int, int] = {}
    for ci, c in enumerate(conjuncts):
        stack = [c]
        while stack:
            t = stack.pop()
            if not has_var[t.tid]:
                continue
            prev = owner.get(t.tid)
            if prev is not None:
                union(ci, prev)
                continue
            owner[t.tid] = ci
            stack.extend(t.args)

    buckets: Dict[Optional[int], List[Term]] = {}
    order: List[Optional[int]] = []
    for ci, c in enumerate(conjuncts):
        key = find(ci) if has_var[c.tid] else None
        if key not in buckets:
            buckets[key] = []
            order.append(key)
        buckets[key].append(c)
    result = [buckets[k] for k in order]
    _split_remember(memo_key, result)
    return result


_split_cache: Dict[frozenset, tuple] = {}

# guards the compound mutations of the shared solver memos (_split_cache,
# _ModelCache) against feasibility-pool worker threads; plain dict reads
# stay lock-free (atomic under the GIL, and a stale miss is harmless)
_cache_lock = threading.Lock()


def _split_remember(key: frozenset, result: List[List[Term]]) -> None:
    with _cache_lock:
        if len(_split_cache) >= 4096:
            _split_cache.clear()
        # tuples of tuples: the cache is shared, so accidental mutation by a
        # future caller raises instead of corrupting unrelated queries
        _split_cache[key] = tuple(tuple(group) for group in result)


def _query_cache():
    from mythril_tpu.querycache import get_query_cache

    return get_query_cache()


def _fast_path(
    conjuncts: Sequence[Term], use_cache: bool = True, replay: bool = True,
    budget_ms: Optional[int] = None,
) -> Tuple[Optional[Tuple[str, Optional["Assignment"]]], List[Term], frozenset]:
    """Cheap solving tiers shared by single-query and batched entry points.

    Tier 0 (structural fold), result memo, the cross-run query cache
    (exact / unsat-core-subsumption / model-reuse tiers), and tier 0.5
    (recent-model replay).  Returns ``(resolved, folded_conjuncts,
    cache_key)`` where ``resolved`` is the final (status, assignment) when
    a cheap tier decided the query, else None.  ``budget_ms`` lets the
    query cache serve stored UNKNOWN verdicts (only to an equal-or-smaller
    budget; None never serves them) — ``resolved`` can therefore be
    UNKNOWN, which callers must treat like their own probe-exhausted
    outcome.
    """
    folded = terms.land(*conjuncts)
    if folded.op == "const":
        if folded.aux:
            return (SAT, Assignment()), [], frozenset()
        return (UNSAT, None), [], frozenset()
    conj = list(folded.args) if folded.op == "and" else [folded]
    key = frozenset(c.tid for c in conj)
    if use_cache:
        hit = _model_cache.results.get(key)
        if hit is not None:
            return hit, conj, key
        qc = _query_cache()
        if qc.enabled:
            # model probing inside the cache mirrors the replay tier below;
            # batched callers (replay=False) replay over a merged union
            # themselves, so they take only the exact/core tiers here
            cached = qc.lookup(conj, budget_ms=budget_ms, probe_models=replay)
            if cached is not None:
                if cached[0] != UNKNOWN:
                    _model_cache.remember(key, cached[0], cached[1])
                return cached, conj, key
    if use_cache and replay:
        # replay only the freshest models: each miss costs a full DAG
        # evaluation, and hits overwhelmingly come from the last few
        # (sibling queries extend the immediately preceding one)
        for asg in reversed(_model_cache.models[-_REPLAY_DEPTH:]):
            try:
                vals = evaluate(conj, asg)
            except Exception:
                continue
            if all(vals[c] for c in conj):
                SolverStatistics().inc("probe_hits")
                _model_cache.remember(key, SAT, asg)
                return (SAT, asg), conj, key
    return None, conj, key


@_otrace.traced("smt.batch_check", cat="smt")
def check_satisfiable_batch(
    constraint_sets: Sequence[Sequence[Term]],
    config: Optional["ProbeConfig"] = None,
    statuses_out: Optional[List[str]] = None,
) -> List[bool]:
    """Frontier-batched pruning: decide many path conditions in one sweep.

    This is SURVEY.md §7's "pruning = batched sat-probing kernel": the engine
    hands over EVERY successor state's constraint set per iteration; cheap
    tiers (structural fold, result memo, recent-model reuse) resolve most,
    and the residue is merged into ONE tape-VM program — sibling states share
    their whole path prefix, so the interned DAGs overlap almost entirely —
    evaluated over a shared candidate pool in a single device dispatch.
    Anything still undecided falls back to the full per-set probe stack.

    Returns one bool per input set (True = keep the state).

    When ``statuses_out`` is given, one status string per set is appended
    to it: ``"sat"`` / ``"unsat"`` / ``"unknown"`` (a timeout decided
    unknown-as-unsat) / ``"prefilter"`` (the abstract pre-filter proved
    UNSAT) / ``"devsolver"`` (the device bit-blast tier proved UNSAT).
    The exploration ledger maps these onto termination classes
    (observability/exploration.VERDICT_CLASS) so a pruned path records
    WHY it stopped, not just that it did.
    """
    config = config or ProbeConfig(
        max_rounds=2, candidates_per_round=24, timeout_ms=2000,
        prune_critical=True, sat_biased=True,
    )
    results: List[Optional[bool]] = [None] * len(constraint_sets)
    statuses: List[Optional[str]] = [None] * len(constraint_sets)
    pending: List[Tuple[int, List[Term], frozenset]] = []

    for i, cs in enumerate(constraint_sets):
        # per-set model replay is deferred: it is batched below over the
        # UNION of pending conjuncts (sibling sets share their whole path
        # prefix, so N separate replays re-walk the same DAG N times)
        resolved, conj, key = _fast_path(
            cs, replay=False, budget_ms=config.timeout_ms
        )
        if resolved is not None:
            if resolved[0] == UNKNOWN:
                # a cached UNKNOWN served at this budget: the prune decision
                # is the same unknown-as-unsat call the cold path would have
                # made, and it must show in the same recall-risk counter
                SolverStatistics().inc("unknown_as_unsat")
                statuses[i] = "unknown"
            results[i] = resolved[0] == SAT
        else:
            pending.append((i, conj, key))

    if pending and _model_cache.models:
        union: List[Term] = []
        seen_tids: set = set()
        for _i, conj, _k in pending:
            for c in conj:
                if c.tid not in seen_tids:
                    seen_tids.add(c.tid)
                    union.append(c)
        for asg in reversed(_model_cache.models[-_REPLAY_DEPTH:]):
            try:
                vals = evaluate(union, asg)
            except Exception:
                # one unevaluable conjunct must not cost every sibling set its
                # cache hit: fall back to per-set replay for this model and
                # let only the sets containing the bad term miss
                vals = None
            still = []
            for i, conj, key in pending:
                try:
                    if vals is None:
                        per_set = evaluate(conj, asg)
                        sat_here = all(per_set[c] for c in conj)
                    else:
                        sat_here = all(vals[c] for c in conj)
                except Exception:
                    still.append((i, conj, key))
                    continue
                if sat_here:
                    SolverStatistics().inc("probe_hits")
                    _model_cache.remember(key, SAT, asg)
                    results[i] = True
                else:
                    still.append((i, conj, key))
            pending = still
            if not pending:
                break

    # Abstract pre-filter over the residue: one vectorized interval +
    # known-bits pass proves many sets UNSAT without bit-blasting; the
    # verdict is sound (bottom-by-abstraction), so it is remembered like
    # any exact UNSAT and the set never reaches the probe stack.
    if pending and getattr(global_args, "prefilter", True):
        from mythril_tpu.absdomain import prefilter_batch

        killed = prefilter_batch([conj for _i, conj, _k in pending])
        still = []
        for (i, conj, key), dead in zip(pending, killed):
            if dead:
                results[i] = False
                statuses[i] = "prefilter"
                _model_cache.remember(key, UNSAT, None)
            else:
                still.append((i, conj, key))
        pending = still

    # Device SAT tier over what survived the pre-filter: narrow sets are
    # bit-blasted and *decided* batched on device (tier 0.65).  UNSAT is
    # exact (remembered like any exact UNSAT and attributed "devsolver"
    # for termination accounting); SAT models arrive concrete_eval-
    # validated and seed the replay cache; UNKNOWN falls through.
    if pending and getattr(global_args, "devsolver", True):
        from mythril_tpu import devsolver

        verdicts = devsolver.decide_batch([conj for _i, conj, _k in pending])
        still = []
        for (i, conj, key), (dstat, asg) in zip(pending, verdicts):
            if dstat == "unsat":
                results[i] = False
                statuses[i] = "devsolver"
                _model_cache.remember(key, UNSAT, None)
            elif dstat == "sat":
                results[i] = True
                _model_cache.remember(key, SAT, asg)
            else:
                still.append((i, conj, key))
        pending = still

    # The merged-dispatch path pays off only when it amortizes over enough
    # sets: a 2-sibling JUMPI fork is cheaper through the per-set stack
    # (model-cache reuse solves the prefix; repair + CDCL finish the flip),
    # measured 3x faster on the killbilly benchmark.  Open-state sweeps and
    # wide forks (>= 3 pending) take the single merged dispatch.
    if (
        len(pending) >= 3
        and _device_backend_requested()
        and _device_worthwhile(
            [c for _i, conj, _k in pending for c in conj],
            config.max_rounds * config.candidates_per_round,
        )
    ):
        try:
            _batch_probe_device(pending, results, config)
        except Exception as e:
            log.debug("batched device prune failed (%s); per-set fallback", e)

    for i, conj, _key in pending:
        if results[i] is None:
            # replay already happened batched above; don't repeat per set
            status, _ = solve_conjunction(conj, config, replay=False)
            if status == UNKNOWN:
                SolverStatistics().inc("unknown_as_unsat")
                statuses[i] = "unknown"
            results[i] = status == SAT
    if statuses_out is not None:
        statuses_out.extend(
            s if s is not None else ("sat" if r else "unsat")
            for s, r in zip(statuses, results)
        )
    return [bool(r) for r in results]


def _batch_probe_device(pending, results, config) -> None:
    """One tape-VM dispatch deciding several constraint sets at once."""
    from mythril_tpu.ops import tape_vm

    if getattr(global_args, "probe_backend", "auto") != "jax":
        if not tape_vm.interpreter_ready():
            tape_vm.ensure_warming()
            return  # host fallback until the interpreter is compiled

    # union of conjuncts in deterministic first-seen order
    all_conjs: List[Term] = []
    col_of: Dict[int, int] = {}
    for _i, conj, _key in pending:
        for c in conj:
            if c.tid not in col_of:
                col_of[c.tid] = len(all_conjs)
                all_conjs.append(c)
    compiled = tape_vm.compile_tape(all_conjs)

    per_set = max(8, (config.max_rounds * config.candidates_per_round) // max(1, len(pending)))
    candidates: List[Assignment] = []
    for _i, conj, _key in pending:
        candidates.extend(CandidateGenerator(conj, config).generate(per_set))
    truth = compiled.evaluate_batch(candidates)  # [B, C_total]

    for i, conj, key in pending:
        cols = [col_of[c.tid] for c in conj]
        rows = truth[:, cols].all(axis=1)
        for b in rows.nonzero()[0]:
            asg = candidates[int(b)]
            try:
                vals = evaluate(conj, asg)
            except Exception:
                continue
            if all(vals[c] for c in conj):
                SolverStatistics().inc("probe_hits")
                _model_cache.remember(key, SAT, asg)
                results[i] = True
                break


# how many recent models the cheap tiers replay per query (each miss costs
# a full DAG evaluation); _ModelCache retention matches this bound
_REPLAY_DEPTH = 6


class _ModelCache:
    """Incremental-solving stand-in: recently found models, tried first.

    Engine queries overwhelmingly *extend* a previous query by one conjunct
    (a JUMPI fork appends one branch condition to the shared path prefix), so
    a model of the prefix usually still satisfies the extension.  Evaluating
    a handful of recent models on the host costs microseconds and skips the
    whole probe (and any device dispatch) on a hit.  Exact results are also
    memoized per interned conjunct-set so repeated reachability checks of the
    same world state are free.
    """

    def __init__(self, max_models: int = _REPLAY_DEPTH, max_results: int = 4096):
        self.models: List[Assignment] = []
        self.results: Dict[frozenset, Tuple[str, Optional[Assignment]]] = {}
        self.max_models = max_models
        self.max_results = max_results

    def remember(self, key: frozenset, status: str, asg: Optional[Assignment]):
        with _cache_lock:
            if len(self.results) >= self.max_results:
                self.results = {}
            self.results[key] = (status, asg)
            if asg is not None:
                # rebind rather than mutate in place: concurrent replay
                # readers iterate whatever list they grabbed, untouched
                models = [m for m in self.models if m is not asg]
                models.append(asg)
                self.models = models[-self.max_models:]


_model_cache = _ModelCache()


def remember_model(conjuncts: Sequence[Term], assignment: Assignment) -> None:
    """Install an externally-found VALIDATED model into the cache/replay
    tiers (e.g. the issue-confirmation gate's session models), so the next
    solve of the same — or an extended — conjunction hits the cheap
    replay tier instead of re-solving.  The caller owns validation."""
    folded = terms.land(*conjuncts)
    if folded.op == "const":
        return
    conj = list(folded.args) if folded.op == "and" else [folded]
    _model_cache.remember(frozenset(c.tid for c in conj), SAT, assignment)
    # the issue-confirmation gate's session models are exactly the SAT
    # verdicts a warm re-run wants back — persist them too
    qc = _query_cache()
    if qc.enabled:
        try:
            qc.record(conj, SAT, assignment)
        except Exception:
            log.debug("query-cache record failed", exc_info=True)


def clear_model_cache() -> None:
    with _cache_lock:
        _model_cache.models = []
        _model_cache.results = {}
        # the split memo holds Term DAGs: clear with the other solver caches
        # so cold-cache measurements stay cold and dropped terms collect
        _split_cache.clear()
    # ditto the query cache's term-id-keyed fingerprint memos (its hash/
    # verdict layers hold no Terms and are reset separately — see
    # querycache.reset_query_cache)
    from mythril_tpu.querycache import clear_query_cache_memos

    clear_query_cache_memos()


def solve_conjunction(
    conjuncts: Sequence[Term],
    config: Optional[ProbeConfig] = None,
    extra_seeds: Optional[Sequence[Assignment]] = None,
    use_cache: bool = True,
    replay: bool = True,
) -> Tuple[str, Optional[Assignment]]:
    """Core entry: find a model of And(conjuncts) or report unsat/unknown.

    ``use_cache=False`` skips both memo tiers — for callers that need a
    fresh model for a constraint set that may have been answered before
    (e.g. differential testing, or re-deriving a model after cache
    invalidation); normal solving should keep the caches on.

    Thin telemetry wrapper: the solve itself lives in
    ``_solve_conjunction_impl``; this layer records one ``smt.solve``
    span (nested per independence-split bucket, since buckets recurse
    through here), a per-query latency histogram, and the verdict into
    the cross-run query cache.
    """
    config = config or ProbeConfig()
    if not _otrace.get_tracer().enabled:
        t0 = time.perf_counter()
        result = _solve_conjunction_impl(
            conjuncts, config, extra_seeds, use_cache, replay
        )
        _metrics_registry().observe("smt.solve_s", time.perf_counter() - t0)
    else:
        with _otrace.span(
            "smt.solve", cat="smt", conjuncts=len(conjuncts)
        ) as sp:
            t0 = time.perf_counter()
            result = _solve_conjunction_impl(
                conjuncts, config, extra_seeds, use_cache, replay
            )
            _metrics_registry().observe("smt.solve_s", time.perf_counter() - t0)
            sp.set(status=result[0])
    if use_cache:
        _record_query_cache(conjuncts, result, config)
    return result


def _record_query_cache(
    conjuncts: Sequence[Term],
    result: Tuple[str, Optional[Assignment]],
    config: ProbeConfig,
) -> None:
    """Persist a solve outcome in the cross-run query cache.

    Recording is idempotent (a verdict that was itself served from the
    cache re-records as a no-op) and covers every tier's outcome — an
    in-process memo/replay SAT is just as valid a cross-run fact as a CDCL
    verdict.  Independence-split buckets recurse through the wrapper, so
    their smaller sub-conjunctions get entries (and unsat cores) of their
    own.  Best-effort: a cache failure must never fail the solve.
    """
    qc = _query_cache()
    if not qc.enabled:
        return
    folded = terms.land(*conjuncts)
    if folded.op == "const":
        return
    conj = list(folded.args) if folded.op == "and" else [folded]
    try:
        qc.record(conj, result[0], result[1], budget_ms=config.timeout_ms)
    except Exception:
        log.debug("query-cache record failed", exc_info=True)


def _solve_conjunction_impl(
    conjuncts: Sequence[Term],
    config: Optional[ProbeConfig] = None,
    extra_seeds: Optional[Sequence[Assignment]] = None,
    use_cache: bool = True,
    replay: bool = True,
) -> Tuple[str, Optional[Assignment]]:
    config = config or ProbeConfig()
    stats = SolverStatistics()
    stats.inc("query_count")
    t0 = time.perf_counter()

    # tiers 0 + memo + query cache + 0.5 (shared with check_satisfiable_batch)
    resolved, conjuncts, cache_key = _fast_path(
        conjuncts, use_cache, replay, budget_ms=config.timeout_ms
    )
    if resolved is not None:
        return resolved

    gen: Optional[CandidateGenerator] = None
    # tier 0.55 (sat-biased queries only): a few directed candidates before
    # any exact-UNSAT machinery.  Pruning sweeps ask "is this successor /
    # this callvalue!=0 variant still feasible" — almost always yes, and
    # the seeder's repair passes hit in 1-3 candidates; paying the interval
    # walk + independence split per sibling first was the dominant harvest
    # cost on wide frontiers (profiled: ~8ms+2.6ms per query x thousands)
    if config.sat_biased and getattr(global_args, "probe_backend", "auto") != "cdcl":
        # (forced-exact mode skips every heuristic tier, this one included)
        gen = CandidateGenerator(conjuncts, config)
        for asg in gen.generate(8, deadline=t0 + config.timeout_ms / 2000.0):
            vals = evaluate(conjuncts, asg)
            if all(vals[c] for c in conjuncts):
                stats.inc("probe_hits")
                if use_cache:
                    _model_cache.remember(cache_key, SAT, asg)
                stats.inc("solver_time", time.perf_counter() - t0)
                return SAT, asg

    # tier 0.58: abstract pre-filter (interval + known-bits over the packed
    # tape) — same bottom-by-abstraction soundness as the tiers below but
    # memoized under the canonical key, so one-shot runs and detection
    # confirmation queries share verdicts with the frontier gate
    if getattr(global_args, "prefilter", True):
        from mythril_tpu.absdomain import refute as _abs_refute

        if _abs_refute(conjuncts):
            if use_cache:
                _model_cache.remember(cache_key, UNSAT, None)
            stats.inc("solver_time", time.perf_counter() - t0)
            return UNSAT, None

    # tier 0.6: interval-bound refutation — exact UNSAT for range-impossible
    # demands (a loop-exit path pinning cnt<=1 conjoined with an overflow
    # demand cnt*value >= 2^256), at one linear DAG walk instead of seconds
    # of 512-bit CDCL blasting
    from mythril_tpu.smt.intervals import refute as _interval_refute

    if _interval_refute(conjuncts):
        if use_cache:
            _model_cache.remember(cache_key, UNSAT, None)
        stats.inc("solver_time", time.perf_counter() - t0)
        return UNSAT, None

    # tier 0.65: device SAT tier — narrow queries (free support within the
    # devsolver bit budget after narrowing) are bit-blasted and *decided*:
    # exact UNSAT, or SAT with a concrete_eval-validated model that seeds
    # the replay cache.  UNKNOWN (wide support, budget lapse, failed
    # validation) falls through to the split/probe/CDCL tiers unchanged.
    if getattr(global_args, "devsolver", True):
        from mythril_tpu import devsolver

        dstat, dasg = devsolver.decide(conjuncts)
        if dstat == "unsat":
            if use_cache:
                _model_cache.remember(cache_key, UNSAT, None)
            stats.inc("solver_time", time.perf_counter() - t0)
            return UNSAT, None
        if dstat == "sat":
            if use_cache:
                _model_cache.remember(cache_key, SAT, dasg)
            stats.inc("solver_time", time.perf_counter() - t0)
            return SAT, dasg

    # tier 0.75: independence split (reference independence_solver.py:86-152)
    # — disjoint-variable buckets solve separately and merge their models
    buckets = independence_split(conjuncts)
    if len(buckets) > 1:
        whole_deadline = t0 + config.timeout_ms / 1000.0
        merged = Assignment()
        for bucket in buckets:
            # buckets share ONE query budget: each recursion gets only the
            # parent's remaining time, never a fresh full timeout
            remaining_ms = max(1, int((whole_deadline - time.perf_counter()) * 1000))
            sub_config = ProbeConfig(
                max_rounds=config.max_rounds,
                candidates_per_round=config.candidates_per_round,
                timeout_ms=remaining_ms,
                rng_seed=config.rng_seed,
                prune_critical=config.prune_critical,
                sat_biased=config.sat_biased,
            )
            status, asg = solve_conjunction(
                bucket, sub_config, extra_seeds=extra_seeds,
                use_cache=use_cache, replay=replay,
            )
            if status == UNSAT:
                if use_cache:
                    _model_cache.remember(cache_key, UNSAT, None)
                return UNSAT, None
            if status != SAT or asg is None:
                return UNKNOWN, None
            # a bucket model may carry assignments for UNRELATED variables
            # (tier 0.5 recycles full models from earlier queries, validated
            # only against this bucket's conjuncts) — merging those would
            # clobber other buckets' witnesses with stale values.  Only the
            # bucket's own free variables may contribute.
            bucket_vars = set(terms.free_vars(bucket))
            merged.scalars.update(
                {k: v for k, v in asg.scalars.items() if k in bucket_vars}
            )
            merged.arrays.update(
                {k: v for k, v in asg.arrays.items() if k in bucket_vars}
            )
            # no ufs merge: the split path rejects 'apply' terms outright, so
            # any uf entries in a bucket model are stale recycled carry-over
        # belt-and-braces: a merged model must satisfy the WHOLE conjunction
        # before it is returned or memoized (an invalid model here poisons
        # the result cache for every later identical query)
        vals = evaluate(conjuncts, merged)
        if all(vals[c] for c in conjuncts):
            stats.inc("probe_hits")
            if use_cache:
                _model_cache.remember(cache_key, SAT, merged)
            return SAT, merged
        log.warning("independence-split merge produced an invalid model; "
                    "falling back to the joint probe")

    # forced-exact mode (recall differential testing, CLI
    # ``--probe-backend cdcl``): skip the heuristic probe entirely; only
    # exact verdicts come back
    if getattr(global_args, "probe_backend", "auto") == "cdcl":
        result: Tuple[str, Optional[Assignment]] = (UNKNOWN, None)
        try:
            from mythril_tpu.native import bitblast

            if bitblast.available():
                stats.inc("cdcl_calls")
                with _otrace.span("smt.cdcl", cat="smt", forced=True):
                    status, asg = bitblast.solve(
                        conjuncts,
                        max(1.0, t0 + config.timeout_ms / 1000.0 - time.perf_counter()),
                    )
                if status == SAT and asg is not None:
                    vals = evaluate(conjuncts, asg)
                    if all(vals[c] for c in conjuncts):
                        _model_cache.remember(cache_key, SAT, asg)
                        result = (SAT, asg)
                elif status == UNSAT:
                    _model_cache.remember(cache_key, UNSAT, None)
                    result = (UNSAT, None)
        except ImportError:
            pass
        stats.inc("solver_time", time.perf_counter() - t0)
        return result

    if gen is None:
        gen = CandidateGenerator(conjuncts, config)
    scalar_vars = gen.scalar_vars
    seeder = gen.seeder
    rng = gen.rng
    deadline = t0 + config.timeout_ms / 1000.0

    def check_asg(asg: Assignment) -> bool:
        vals = evaluate(conjuncts, asg)
        return all(vals[c] for c in conjuncts)

    candidates: List[Assignment] = []
    if extra_seeds:
        candidates.extend(extra_seeds)
    total = config.max_rounds * config.candidates_per_round
    # when the exact tier is cheap (native CDCL present, small blast), cap
    # the heuristic budget at one round: for UNSAT-leaning queries the full
    # candidate stream plus the 64-mutation repair costs more than the
    # exact answer (profiled ~190k candidate evaluations per wide_solc run)
    cheap_exact = False
    if total > config.candidates_per_round:
        try:
            from mythril_tpu.native import bitblast as _bb

            cheap_exact = (
                _bb.available()
                and len(terms.topo_order(list(conjuncts))) < 1500
            )
        except Exception:
            cheap_exact = False
        if cheap_exact:
            total = config.candidates_per_round

    # Device batching only when the deadline still has room: a cache-miss
    # compile is the dominant cost, and a blown solver_timeout breaks the
    # engine's wall-clock budgeting.
    compiled = (
        _try_compile_device(conjuncts)
        if _device_backend_requested()
        and _device_worthwhile(conjuncts, total + len(candidates))
        and time.perf_counter() < deadline
        else None
    )
    if compiled is not None:
        # the batched dispatch needs the whole pool upfront
        with _otrace.span("smt.candidates", cat="smt", n=total):
            candidates.extend(gen.generate(total, deadline))

    best_asg, best_score = None, -1
    if compiled is not None:
        # Batched path: every candidate in one XLA dispatch, then host
        # validation of the winner (exactness belt-and-braces).
        import numpy as _np

        try:
            with _otrace.span(
                "smt.device_probe", cat="device", batch=len(candidates)
            ), _otrace.device_annotation("smt.device_probe"):
                truth = _evaluate_candidates_device(compiled, candidates)  # [B, C]
        except Exception as e:
            log.warning(
                "device probe evaluation failed, host fallback (%s): %s",
                type(e).__name__,
                e,
            )
            compiled = None
        else:
            scores = truth.sum(axis=1)
            for b in _np.argsort(-scores, kind="stable"):
                if scores[b] < len(conjuncts):
                    break
                if check_asg(candidates[b]):
                    stats.inc("probe_hits")
                    stats.inc("solver_time", time.perf_counter() - t0)
                    _model_cache.remember(cache_key, SAT, candidates[b])
                    return SAT, candidates[b]
                if time.perf_counter() > deadline:
                    break
            if len(candidates):
                b = int(_np.argmax(scores))
                best_score, best_asg = int(scores[b]), candidates[b]
    if compiled is None:
        # host path: STREAM candidates — directed builds (hint + repair
        # passes) are expensive, and on well-hinted queries the first one
        # already satisfies; building the whole pool upfront wastes
        # (total - 1) builds per query across a wide frontier
        def streamed():
            yield from candidates
            remaining = total - max(0, len(candidates) - len(extra_seeds or ()))
            for _ in range(max(0, remaining)):
                if time.perf_counter() > deadline:
                    return
                yield gen.generate(1)[0]

        for asg in streamed():
            try:
                vals = evaluate(conjuncts, asg)
            except NotImplementedError:
                continue
            score = sum(1 for c in conjuncts if vals[c])
            if score == len(conjuncts):
                stats.inc("probe_hits")
                stats.inc("solver_time", time.perf_counter() - t0)
                _model_cache.remember(cache_key, SAT, asg)
                return SAT, asg
            if score > best_score:
                best_score, best_asg = score, asg
            if time.perf_counter() > deadline:
                break

    # local repair: mutate the best candidate on vars feeding failed conjuncts
    if best_asg is not None and scalar_vars:
        for _ in range(16 if cheap_exact else 64):
            if time.perf_counter() > deadline:
                break
            asg = Assignment(
                dict(best_asg.scalars),
                {k: ArrayValue(v.backing, v.default) for k, v in best_asg.arrays.items()},
            )
            v = rng.choice(scalar_vars)
            if v.sort is terms.BOOL:
                asg.scalars[v] = not asg.scalars.get(v, False)
            else:
                mode = rng.random()
                cur = asg.scalars.get(v, 0)
                if mode < 0.3:
                    asg.scalars[v] = mask(cur + rng.choice([1, -1, 2, -2, 32, -32]), v.width)
                elif mode < 0.6:
                    asg.scalars[v] = cur ^ (1 << rng.randint(0, v.width - 1))
                elif mode < 0.8 and seeder.const_pool:
                    asg.scalars[v] = mask(rng.choice(seeder.const_pool), v.width)
                else:
                    asg.scalars[v] = rng.getrandbits(v.width)
            vals = evaluate(conjuncts, asg)
            score = sum(1 for c in conjuncts if vals[c])
            if score == len(conjuncts):
                stats.inc("probe_hits")
                stats.inc("solver_time", time.perf_counter() - t0)
                _model_cache.remember(cache_key, SAT, asg)
                return SAT, asg
            if score >= best_score:
                best_score, best_asg = score, asg

    # tier 2: exact bit-blasting CDCL if the native library is available
    try:
        from mythril_tpu.native import bitblast

        if bitblast.available():
            stats.inc("cdcl_calls")
            budget = deadline - time.perf_counter()
            if compiled is not None or config.prune_critical:
                # device-path queries may have burned the deadline on an XLA
                # compile (first bucket in a cold process), and prune-critical
                # queries kill paths on this verdict — guarantee the exact
                # tier a minimal slice instead of silently disabling it with
                # a nonpositive timeout.  Other host-only queries keep strict
                # wall-clock discipline (mutation pruner's 500ms etc.).
                budget = max(1.0, budget)
            with _otrace.span("smt.cdcl", cat="smt", conjuncts=len(conjuncts)):
                status, asg = bitblast.solve(conjuncts, budget)
            stats.inc("solver_time", time.perf_counter() - t0)
            if status == SAT and asg is not None and check_asg(asg):
                _model_cache.remember(cache_key, SAT, asg)
                return SAT, asg
            if status == UNSAT:
                # exact verdict: safe to memoize (UNKNOWN never is — a larger
                # budget on a later identical query may still find a model)
                _model_cache.remember(cache_key, UNSAT, None)
                return UNSAT, None
    except ImportError:
        pass

    stats.inc("solver_time", time.perf_counter() - t0)
    return UNKNOWN, None


# ---------------------------------------------------------------------------
# Solver / Optimize facades (reference smt/solver/solver.py:83-121)
# ---------------------------------------------------------------------------


class Solver:
    def __init__(self, config: Optional[ProbeConfig] = None):
        self.config = config or ProbeConfig()
        self.constraints: List = []
        self._model: Optional[Model] = None

    def set_timeout(self, timeout_ms: int) -> None:
        self.config.timeout_ms = timeout_ms

    def add(self, *constraints) -> None:
        for c in constraints:
            if isinstance(c, (list, tuple)):
                self.constraints.extend(c)
            else:
                self.constraints.append(c)

    append = add

    def _raw_conjuncts(self) -> List[Term]:
        return [c.raw if hasattr(c, "raw") else c for c in self.constraints]

    def check(self, *extra) -> str:
        conj = self._raw_conjuncts() + [
            c.raw if hasattr(c, "raw") else c for c in extra
        ]
        status, asg = solve_conjunction(conj, self.config)
        self._model = Model(asg) if asg is not None else None
        return status

    def model(self) -> Model:
        if self._model is None:
            raise UnsatError("no model available (last check was not sat)")
        return self._model

    def reset(self) -> None:
        self.constraints = []
        self._model = None


class Optimize(Solver):
    """Exact objective optimization via CDCL-backed bound search.

    The reference uses z3.Optimize to minimize calldata size / callvalue for
    exploit reports (mythril/analysis/solver.py:216-256, smt/solver/
    solver.py:109-121).  Here each objective is refined lexicographically:
    starting from any model, repeatedly assert ``obj <= mid`` (binary search
    tightened by each new model's actual value) until the CDCL tier proves
    the bound unsatisfiable — that bound is then the exact optimum and is
    pinned (``obj == opt``) before refining the next objective.  If a bound
    query comes back UNKNOWN (probe exhausted, no native CDCL) the best
    model found so far is kept — never worse than a single plain check.
    """

    # per-objective refinement budget: enough for calldata-size-style
    # objectives (optima near 0 converge in a handful of steps) while
    # bounding pathological 256-bit searches
    MAX_BOUND_STEPS = 48

    def __init__(
        self,
        config: Optional[ProbeConfig] = None,
        session=None,
        session_enable: Sequence[int] = (),
    ):
        """``session``/``session_enable``: an externally-owned live native
        OptimizeSession (e.g. the transaction-end issue gate's, which has
        already blasted the shared path prefix with per-issue enable
        literals and THESE objectives in THIS order) answers every query
        under assumptions instead of paying a fresh blast.  The caller
        keeps ownership: check() never closes an external session."""
        super().__init__(config)
        self._minimize: List = []
        self._maximize: List = []
        self._ext_session = session
        self._ext_enable = tuple(session_enable)
        # True after check() iff EVERY objective was refined to a PROVEN
        # optimum (callers use this to decide whether the model is safe to
        # memoize budget-independently; a truncated refinement is not)
        self.proven_optimal = True

    def minimize(self, expr) -> None:
        self._minimize.append(expr.raw if hasattr(expr, "raw") else expr)

    def maximize(self, expr) -> None:
        self._maximize.append(expr.raw if hasattr(expr, "raw") else expr)

    def _refine(self, conj, obj, asg, deadline: float, want_min: bool,
                session=None, obj_idx: int = 0, pins=()):
        """Tighten one objective to its proven optimum (or best effort).

        With an incremental CDCL ``session`` (native OptimizeSession), each
        bound query is answered under assumptions against the ONCE-blasted
        formula — learned clauses persist, so the whole binary search costs
        about one solver call.  Session SAT models are validated exactly;
        an invalid one (keccak abstraction) falls back to the probe stack
        for that query.  ``pins`` are the bounds already fixed for earlier
        objectives (lexicographic ordering)."""
        width = obj.width
        top = (1 << width) - 1
        def cfg_step() -> ProbeConfig:
            # clamp each step to the remaining overall budget so check()
            # cannot overrun its single deadline by a step's full slice
            remaining_ms = max(1, int((deadline - time.perf_counter()) * 1000))
            return ProbeConfig(
                max_rounds=self.config.max_rounds,
                candidates_per_round=self.config.candidates_per_round,
                timeout_ms=min(max(1, self.config.timeout_ms // 4), remaining_ms),
                rng_seed=self.config.rng_seed,
            )

        def value(a) -> int:
            return evaluate([obj], a)[obj]

        def bound_term(op: str, v: int):
            c = terms.const(v, width)
            if op == "le":
                return terms.ule(obj, c)
            if op == "ge":
                return terms.uge(obj, c)
            return terms.eq(obj, c)

        def ask_op(op: str, v: int):
            bt = bound_term(op, v)
            if session is not None:
                SolverStatistics().inc("cdcl_calls")
                budget = max(0.05, min(
                    self.config.timeout_ms / 4000.0, deadline - time.perf_counter()
                ))
                st, a2 = session.solve(
                    list(pins) + [(obj_idx, op, v)], budget,
                    enable=self._ext_enable if session is self._ext_session
                    else (),
                )
                if st == UNSAT:
                    return UNSAT, None
                if st == SAT and a2 is not None:
                    vals = evaluate(conj + [bt], a2)
                    if all(vals[c] for c in conj) and vals[bt]:
                        return SAT, a2
                    # abstraction artifact: exact validation failed — the
                    # probe stack owns this query (a true model may exist)
            return solve_conjunction(conj + [bt], cfg_step())

        best = value(asg)
        # fast path: the global optimum in one query
        target = 0 if want_min else top
        if best != target and time.perf_counter() < deadline:
            status, a2 = ask_op("eq", target)
            if status == SAT and a2 is not None:
                return a2, True
        steps = 0
        # value bisection over a w-bit range needs up to w steps to converge
        # exactly; with an incremental session each step is ~a propagation,
        # so the budget is the width (the probe path keeps the tight cap)
        max_steps = (width + 16) if session is not None else self.MAX_BOUND_STEPS

        if want_min:
            lo, hi = 0, best
        else:
            # exponential-up first: a hi anchor of 2^width would need ~width
            # halvings; doubling from the current model reaches the optimum's
            # magnitude in log2(opt) SAT steps and one UNSAT caps the range
            lo, hi = best, top
            while lo < hi and steps < max_steps and time.perf_counter() < deadline:
                steps += 1
                probe_to = min(2 * best + 1, top)
                status, a2 = ask_op("ge", probe_to)
                if status == SAT and a2 is not None:
                    asg, best = a2, value(a2)
                    lo = best
                    if best >= top:
                        return asg, True
                elif status == UNSAT:
                    hi = probe_to - 1
                    break
                else:
                    return asg, False
        proven = best == target
        while lo < hi and steps < max_steps and time.perf_counter() < deadline:
            steps += 1
            if want_min:
                mid = lo + (hi - 1 - lo) // 2  # strictly below current best
                status, a2 = ask_op("le", mid)
            else:
                mid = hi - (hi - lo - 1) // 2  # strictly above current best
                status, a2 = ask_op("ge", mid)
            if status == SAT and a2 is not None:
                asg, best = a2, value(a2)
                if want_min:
                    hi = best
                else:
                    lo = best
            elif status == UNSAT:  # exact verdict from the CDCL tier
                if want_min:
                    lo = mid + 1
                else:
                    hi = mid - 1
                proven = lo >= hi
            else:  # UNKNOWN: keep the best model found so far
                return asg, False
        return asg, proven or lo >= hi

    def check(self, *extra) -> str:
        conj = self._raw_conjuncts() + [
            c.raw if hasattr(c, "raw") else c for c in extra
        ]
        # ONE timeout budget covers the initial solve AND all refinement
        # (support/model.py sizes it against the remaining execution time)
        deadline = time.perf_counter() + self.config.timeout_ms / 1000.0
        objectives = [(m, True) for m in self._minimize] + [
            (m, False) for m in self._maximize
        ]
        # initial solve: cheap tiers (fold/memo/replay) first — only a query
        # they cannot answer pays for blasting an incremental CDCL session,
        # which then serves the initial solve AND every bound query of every
        # objective against the once-blasted formula (pins carry earlier
        # objectives' achieved bounds as assumptions); unsupported structure
        # degrades to the per-query probe/CDCL stack
        status, asg = UNKNOWN, None
        resolved, folded_conj, cache_key = _fast_path(conj)
        if resolved is not None:
            status, asg = resolved
            if status != SAT or asg is None:
                # cheap-tier UNSAT: no session was ever built, nothing to pay
                self._model = None
                return status
        session = None
        owns_session = True
        if status != UNSAT and objectives:
            if self._ext_session is not None:
                # the caller's live session (issue gate) already blasted
                # this formula family — reuse it, learned clauses and all
                session = self._ext_session
                owns_session = False
            else:
                try:
                    from mythril_tpu.native import bitblast

                    if bitblast.available():
                        session = bitblast.OptimizeSession(
                            conj, [obj for obj, _ in objectives]
                        )
                except Exception as e:
                    log.debug("optimize session unavailable: %s", e)
                    session = None
        if status == UNKNOWN and session is not None:
            SolverStatistics().inc("cdcl_calls")
            st, a = session.solve(
                [], max(0.05, min(self.config.timeout_ms / 2000.0,
                                  deadline - time.perf_counter())),
                enable=self._ext_enable if not owns_session else (),
            )
            if st == UNSAT:
                _model_cache.remember(cache_key, UNSAT, None)
                status = UNSAT
            elif st == SAT and a is not None:
                vals = evaluate(folded_conj, a)
                if all(vals[c] for c in folded_conj):
                    _model_cache.remember(cache_key, SAT, a)
                    status, asg = SAT, a
        if status == UNKNOWN:
            status, asg = solve_conjunction(conj, self.config)
        if status != SAT or asg is None:
            self._model = None
            if session is not None and owns_session:
                session.close()
            return status
        pins: List = []
        self.proven_optimal = True
        try:
            # lexicographic: each objective's achievement is pinned before
            # the next — exactly (==) when proven optimal, as a bound
            # (<=/>=) when refinement gave up, so later objectives can
            # never regress it
            for i, (obj, want_min) in enumerate(objectives):
                asg, proven = self._refine(
                    conj, obj, asg, deadline, want_min,
                    session=session, obj_idx=i, pins=pins,
                )
                self.proven_optimal = self.proven_optimal and proven
                achieved_val = evaluate([obj], asg)[obj]
                achieved = terms.const(achieved_val, obj.width)
                if proven:
                    conj = conj + [terms.eq(obj, achieved)]
                    pins.append((i, "eq", achieved_val))
                elif want_min:
                    conj = conj + [terms.ule(obj, achieved)]
                    pins.append((i, "le", achieved_val))
                else:
                    conj = conj + [terms.uge(obj, achieved)]
                    pins.append((i, "ge", achieved_val))
        finally:
            if session is not None and owns_session:
                session.close()
        self._model = Model(asg)
        return SAT
