"""HostArena generation isolation and the pipelined freeze guard.

The double-buffered pipeline keeps two analyses' arenas alive at once
(engine N's drain overlapping engine N+1's seeding); rows, memos and
records must never alias across instances, and host appends must be
impossible while a device segment owns the append indices.
"""

import numpy as np
import pytest

from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier.arena import HostArena


def test_generations_share_no_buffers():
    a = HostArena(cap=64)
    b = HostArena(cap=64)
    assert a.generation != b.generation, "generation ids must be unique"
    for name in ("op", "a", "b", "c", "width", "val", "isconst", "taint"):
        col_a, col_b = getattr(a, name), getattr(b, name)
        assert col_a is not col_b
        assert not np.shares_memory(col_a, col_b), (
            f"column {name} aliases across generations"
        )


def test_const_interning_is_per_instance():
    a = HostArena(cap=64)
    b = HostArena(cap=64)
    row_a = a.const_row(0xDEAD)
    assert a.const_row(0xDEAD) == row_a, "interning memo broken"
    # b never saw the append: its memo and columns are untouched
    assert b.length < a.length
    row_b = b.const_row(0xBEEF)
    assert a.val[row_a, 0] != b.val[row_b, 0]
    assert b._const_memo is not a._const_memo


def test_freeze_blocks_appends_until_thaw():
    arena = HostArena(cap=64)
    arena.const_row(1)
    arena.freeze()
    with pytest.raises(RuntimeError, match="frozen"):
        arena._append(O.A_CONST, width=256, value=99)
    with pytest.raises(RuntimeError, match="frozen"):
        arena.const_row(99)  # un-memoized const must append, so it raises
    n = arena.length
    arena.thaw()
    arena.const_row(99)
    assert arena.length == n + 1


def test_freeze_does_not_block_memoized_reads():
    arena = HostArena(cap=64)
    row = arena.const_row(7)
    arena.freeze()
    # interned row already exists: lookup is read-only and must survive
    assert arena.const_row(7) == row
    arena.thaw()
