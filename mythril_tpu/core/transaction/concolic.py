"""Concrete (concolic) transaction drivers: replay recorded transactions.

Reference parity: mythril/laser/ethereum/transaction/concolic.py:23-172.
"""

from __future__ import annotations

from typing import List, Optional

from mythril_tpu.core.state.calldata import ConcreteCalldata
from mythril_tpu.core.transaction.transaction_models import (
    ContractCreationTransaction,
    MessageCallTransaction,
    tx_id_manager,
)
from mythril_tpu.frontend.disassembler import Disassembly
from mythril_tpu.smt import symbol_factory


def execute_message_call(
    laser_evm,
    callee_address,
    caller_address,
    origin_address,
    code,
    data: List[int],
    gas_limit: int,
    gas_price: int,
    value: int,
    track_gas: bool = False,
    block_env: Optional[dict] = None,
):
    """Replay one concrete message call (reference :75-130).

    ``block_env`` maps Environment attribute names (block_number, timestamp,
    coinbase, difficulty, block_gaslimit) to concrete BitVecs so fixtures
    with known block parameters replay exactly."""
    open_states = laser_evm.open_states[:]
    del laser_evm.open_states[:]
    result = []
    for open_world_state in open_states:
        next_tx_id = tx_id_manager.get_next_tx_id()
        transaction = MessageCallTransaction(
            world_state=open_world_state,
            identifier=next_tx_id,
            gas_limit=gas_limit,
            origin=_bv(origin_address),
            caller=_bv(caller_address),
            callee_account=open_world_state[_to_int(callee_address)],
            call_data=ConcreteCalldata(next_tx_id, data),
            gas_price=_bv(gas_price),
            call_value=_bv(value),
            static=False,
            block_env=block_env,
        )
        _setup(laser_evm, transaction)
        result = laser_evm.exec(track_gas=track_gas)
    return result


def execute_contract_creation(
    laser_evm,
    contract_initialization_code: str,
    caller_address,
    origin_address,
    world_state=None,
    gas_limit: int = 8_000_000,
    gas_price: int = 0,
    value: int = 0,
    contract_name: Optional[str] = None,
    track_gas: bool = False,
):
    """Replay a concrete creation transaction (reference :23-72)."""
    from mythril_tpu.core.state.world_state import WorldState

    world_state = world_state or WorldState()
    del laser_evm.open_states[:]
    next_tx_id = tx_id_manager.get_next_tx_id()
    transaction = ContractCreationTransaction(
        world_state=world_state,
        identifier=next_tx_id,
        gas_limit=gas_limit,
        origin=_bv(origin_address),
        caller=_bv(caller_address),
        code=Disassembly(bytes.fromhex(contract_initialization_code.replace("0x", ""))),
        # concrete replay: constructor args are embedded in the creation
        # hex — the symbolic constructor-arg default must not apply
        call_data=ConcreteCalldata(next_tx_id, []),
        gas_price=_bv(gas_price),
        call_value=_bv(value),
        contract_name=contract_name,
    )
    _setup(laser_evm, transaction)
    result = laser_evm.exec(create=True, track_gas=track_gas)
    return transaction.callee_account, result


def _setup(laser_evm, transaction) -> None:
    global_state = transaction.initial_global_state()
    global_state.transaction_stack.append((transaction, None))
    global_state.world_state.transaction_sequence.append(transaction)
    if laser_evm.requires_statespace:
        from mythril_tpu.core.cfg import Node

        node = Node(
            transaction.callee_account.contract_name
            if transaction.callee_account
            else "unknown"
        )
        laser_evm.nodes[node.uid] = node
        global_state.node = node
        global_state.world_state.node = node
    laser_evm.work_list.append(global_state)


def _bv(value):
    if isinstance(value, int):
        return symbol_factory.BitVecVal(value, 256)
    if isinstance(value, str):
        return symbol_factory.BitVecVal(int(value, 16), 256)
    return value


def _to_int(value) -> int:
    if isinstance(value, str):
        return int(value, 16)
    if isinstance(value, int):
        return value
    return value.value
