"""Path condition: an append-only list of Bool terms.

Reference parity: mythril/laser/ethereum/state/constraints.py:10-109.
``is_possible`` is the engine's pruning question — answered by the probe/CDCL
stack here rather than Z3.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from mythril_tpu.smt import Bool, symbol_factory
from mythril_tpu.smt.solver import (
    ProbeConfig,
    SAT,
    UNKNOWN,
    SolverStatistics,
    solve_conjunction,
)


class Constraints(list):
    def __init__(self, constraint_list: Optional[Iterable[Bool]] = None):
        super().__init__(constraint_list or [])

    def append(self, constraint) -> None:
        if isinstance(constraint, bool):
            constraint = symbol_factory.BoolVal(constraint)
        super().append(constraint)

    @property
    def is_possible(self) -> bool:
        """Quick satisfiability probe used for successor pruning."""
        status, _ = solve_conjunction(
            self.get_all_raw(),
            ProbeConfig(
                max_rounds=2,
                candidates_per_round=24,
                timeout_ms=2000,
                prune_critical=True,
            ),
        )
        if status == UNKNOWN:
            SolverStatistics().unknown_as_unsat += 1
        return status == SAT

    def get_all_constraints(self) -> "Constraints":
        return Constraints(self)

    def get_all_raw(self) -> List:
        return [c.raw if hasattr(c, "raw") else c for c in self]

    def __copy__(self) -> "Constraints":
        return Constraints(self)

    def copy(self) -> "Constraints":
        return Constraints(self)

    def __add__(self, other) -> "Constraints":
        out = Constraints(self)
        for c in other:
            out.append(c)
        return out
