"""Pallas TPU kernel for the batched keccak-f[1600] permutation.

This is the hand-scheduled version of ``keccak_jax.keccak_f1600`` (the
SURVEY.md §2.9 "Pallas keccak-f[1600] kernel (batched)" item): the probe
solver hashes thousands of candidate preimages per dispatch, and the
permutation is the dominant cost of every ``keccak`` term.

Layout: the [..., 25, 4]-limb state (25 lanes x four 16-bit limbs held in
uint32, see mythril_tpu/ops/bitvec.py) is transposed to a ``(100, B)`` tile —
rows are lane-major limbs, the batch rides the 128-wide lane dimension of the
VPU — so every theta/rho/pi/chi shuffle is a *static* gather over the leading
(sublane) axis and every xor/shift is an 8x128 vector op.  The 24 rounds run
in a ``fori_loop`` with round constants scalar-prefetched from SMEM, keeping
the whole permutation resident in VMEM with zero HBM round-trips between
rounds.

Numerical contract: bit-identical to ``keccak_jax.keccak_f1600`` (differential
test: tests/ops/test_keccak_pallas.py, in interpreter mode on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import mythril_tpu
from mythril_tpu.ops.bitvec import LIMB_BITS, LIMB_MASK
from mythril_tpu.ops.keccak_jax import _PI_ROT, _PI_SRC, _RC_LIMBS

mythril_tpu.enable_persistent_compilation_cache()

# Row index tables for the flattened (100 = lane*4 + limb, B) layout.
# rho+pi as one fused static row gather: out_row[dst*4 + j] combines
# src rows rotated by q limbs plus a sub-limb shift borrowing from the
# previous limb (limbs are < 2^16, so ``prev >> 16`` vanishes when s == 0).
_ROT_Q = _PI_ROT // LIMB_BITS
_ROT_S = _PI_ROT % LIMB_BITS
_RHOPI_MAIN = np.zeros(100, np.int32)
_RHOPI_PREV = np.zeros(100, np.int32)
_RHOPI_SHIFT = np.zeros((100, 1), np.uint32)
for _dst in range(25):
    for _j in range(4):
        _src = _PI_SRC[_dst]
        _q, _s = int(_ROT_Q[_dst]), int(_ROT_S[_dst])
        _RHOPI_MAIN[_dst * 4 + _j] = _src * 4 + (_j - _q) % 4
        _RHOPI_PREV[_dst * 4 + _j] = _src * 4 + (_j - _q - 1) % 4
        _RHOPI_SHIFT[_dst * 4 + _j, 0] = _s

# theta: parity column x feeds lanes x, x+5, ...; d[x] = c[x-1] ^ rotl1(c[x+1])
_THETA_ROWS = np.array(
    [[(x + 5 * y) * 4 + j for y in range(5)] for x in range(5) for j in range(4)],
    np.int32,
)  # [20, 5] rows to xor per parity limb (20 = 5 columns x 4 limbs)
_D_FOR_ROW = np.array(
    [((i // 4) % 5) * 4 + (i % 4) for i in range(100)], np.int32
)  # state row -> d row (d laid out as [20, B], x-major limbs)

# chi: out = b ^ (~b[x+1] & b[x+2]) on the x coordinate
_CHI1_ROWS = np.array(
    [(((i // 4) % 5 + 1) % 5 + 5 * (i // 20)) * 4 + i % 4 for i in range(100)],
    np.int32,
)
_CHI2_ROWS = np.array(
    [(((i // 4) % 5 + 2) % 5 + 5 * (i // 20)) * 4 + i % 4 for i in range(100)],
    np.int32,
)
# d[x] gathers: c rows for x-1 and x+1 (c laid out as [20, B], x-major limbs)
_DM1_ROWS = np.array(
    [((x + 4) % 5) * 4 + j for x in range(5) for j in range(4)], np.int32
)
_DP1_MAIN = np.zeros(20, np.int32)  # rotl1 over the 64-bit lane of c[x+1]
_DP1_PREV = np.zeros(20, np.int32)
for _x in range(5):
    for _j in range(4):
        _DP1_MAIN[_x * 4 + _j] = ((_x + 1) % 5) * 4 + _j  # shift 1 within limb
        _DP1_PREV[_x * 4 + _j] = ((_x + 1) % 5) * 4 + (_j - 1) % 4


def _round_body(r, st, rc_ref):
    """One keccak-f round on the (100, B) uint32 tile.

    All shuffle tables are compile-time Python constants, so every gather is
    written as static row slicing + one concatenate — Pallas kernels cannot
    capture traced index arrays (they would become implicit constants).
    """
    row = [st[i : i + 1, :] for i in range(100)]

    # theta parity: c[x*4+j] = xor over the column's five lanes
    c = []
    for i in range(20):
        acc = row[_THETA_ROWS[i, 0]]
        for y in range(1, 5):
            acc = acc ^ row[_THETA_ROWS[i, y]]
        c.append(acc)
    # d[x] = c[x-1] ^ rotl1(c[x+1])
    d = []
    for i in range(20):
        rot1 = (
            (c[_DP1_MAIN[i]] << 1) | (c[_DP1_PREV[i]] >> (LIMB_BITS - 1))
        ) & LIMB_MASK
        d.append(c[_DM1_ROWS[i]] ^ rot1)
    a = [row[i] ^ d[_D_FOR_ROW[i]] for i in range(100)]

    # rho + pi: per-row static sub-limb shift over the gathered source rows
    b = []
    for i in range(100):
        s = int(_RHOPI_SHIFT[i, 0])
        main, prev = a[_RHOPI_MAIN[i]], a[_RHOPI_PREV[i]]
        b.append(((main << s) | (prev >> (LIMB_BITS - s))) & LIMB_MASK)

    # chi + iota (round constant limbs read from SMEM)
    out = [
        b[i] ^ ((b[_CHI1_ROWS[i]] ^ LIMB_MASK) & b[_CHI2_ROWS[i]])
        for i in range(100)
    ]
    for j in range(4):
        out[j] = out[j] ^ rc_ref[r, j]
    return jnp.concatenate(out, axis=0)


def _kernel(rc_ref, st_ref, out_ref):
    st = st_ref[:]
    st = jax.lax.fori_loop(
        0, 24, lambda r, s: _round_body(r, s, rc_ref), st, unroll=False
    )
    out_ref[:] = st


# lanes per grid step: the round body holds several (100, BT) temporaries in
# VMEM; 1024 lanes keeps the scoped allocation well under the ~16MB limit
# (observed: 4096 lanes in one block exceeds it)
_LANE_TILE = 1024


@functools.partial(jax.jit, static_argnames=("interpret",))
def _permute_tile(tile: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Run keccak-f[1600] on a (100, B) tile (B a multiple of 128).

    Large batches are tiled along the lane axis with a pallas grid so each
    block's working set stays within scoped VMEM."""
    B = tile.shape[1]
    bt = min(B, _LANE_TILE)
    grid = (B + bt - 1) // bt
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct(tile.shape, jnp.uint32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((100, bt), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((100, bt), lambda i: (0, i), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(jnp.asarray(_RC_LIMBS), tile)


def keccak_f1600(state: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Drop-in for ``keccak_jax.keccak_f1600``: [..., 25, 4] -> [..., 25, 4].

    Flattens the batch onto the 128-lane axis (padded up), permutes in one
    pallas dispatch, and restores the original layout.
    """
    batch_shape = state.shape[:-2]
    flat = state.reshape((-1, 25, 4))
    b = flat.shape[0]
    bp = max(128, ((b + 127) // 128) * 128)
    if bp != b:
        flat = jnp.pad(flat, ((0, bp - b), (0, 0), (0, 0)))
    tile = flat.reshape(bp, 100).T  # rows = lane*4 + limb
    out = _permute_tile(tile, interpret=interpret)
    return out.T[:b].reshape(batch_shape + (25, 4))
