"""TxOrigin: control flow depends on tx.origin (SWC-115).

Reference parity: mythril/analysis/module/modules/dependence_on_origin.py:1-112
— ORIGIN results are taint-annotated; a JUMPI whose condition carries the
taint raises the issue.
"""

from __future__ import annotations

from typing import List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import TX_ORIGIN_USAGE
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.frontier import taint

DESCRIPTION = "Check whether control flow decisions are influenced by tx.origin."


class TxOriginAnnotation:
    """Taint marker set on the ORIGIN opcode's result."""


taint.register(
    taint.TAINT_ORIGIN,
    TxOriginAnnotation,
    lambda a: isinstance(a, TxOriginAnnotation),
)


class TxOrigin(DetectionModule):
    name = "Control flow depends on tx.origin"
    swc_id = TX_ORIGIN_USAGE
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["JUMPI"]
    post_hooks = ["ORIGIN"]
    # the ORIGIN post-hook only annotates the pushed value; the frontier
    # reproduces it from the seeded taint bit on the origin env row, so
    # device-executed ORIGINs ship no event (frontier/taint.py)
    taint_source_hooks = {"ORIGIN": taint.TAINT_ORIGIN}
    # staticpass: issues only exist where an ORIGIN value may influence a
    # JUMPI condition
    static_required_ops = frozenset({"ORIGIN"})
    static_taint_sources = {"ORIGIN": taint.TAINT_ORIGIN}
    static_taint_sinks = frozenset({"JUMPI"})

    def _execute(self, state: GlobalState) -> Optional[List[Issue]]:
        if self._cache_key(state) in self.cache:
            return None
        return self._analyze_state(state)

    def _analyze_state(self, state: GlobalState) -> List[Issue]:
        if state.get_current_instruction()["opcode"] != "JUMPI":
            # post-ORIGIN: annotate the pushed value
            state.mstate.stack[-1].annotate(TxOriginAnnotation())
            return []

        condition = state.mstate.stack[-2]
        if not any(isinstance(a, TxOriginAnnotation) for a in condition.annotations):
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints.get_all_constraints()
            )
        except UnsatError:
            return []
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.node.function_name if state.node else "unknown",
                address=state.get_current_instruction()["address"],
                swc_id=TX_ORIGIN_USAGE,
                title="Dependence on tx.origin",
                severity="Low",
                bytecode=state.environment.code.bytecode,
                description_head="Use of tx.origin as a part of authorization control.",
                description_tail=(
                    "The tx.origin environment variable has been found to "
                    "influence a control flow decision. Note that using tx.origin "
                    "as a security control might cause a situation where a user "
                    "inadvertently authorizes a smart contract to perform an "
                    "action on their behalf. It is recommended to use msg.sender "
                    "instead."
                ),
                gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
                transaction_sequence=transaction_sequence,
            )
        ]


detector = TxOrigin
