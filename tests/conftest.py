"""Test harness config: run all JAX work on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on virtual CPU devices exactly as the driver's dryrun does.

The environment may pre-register an external TPU backend plugin and pin
``JAX_PLATFORMS`` to it at interpreter start (sitecustomize), so an env-var
setdefault is not enough: explicitly override the platform through
``jax.config`` before any backend is initialized.  This also keeps the suite
hermetic when the TPU tunnel is unavailable.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
