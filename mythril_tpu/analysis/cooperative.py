"""Cooperative corpus analysis: many contracts, one device frontier.

The reference analyzes a corpus strictly sequentially — one contract, one
full symbolic execution, next contract (reference mythril/mythril/
mythril_analyzer.py:138-175).  On a TPU that serializes exactly the axis the
hardware wants to batch: each small contract's frontier is too narrow to
amortize segment dispatches, so per-contract runs stay host-bound.

This driver instead runs the per-contract transaction loops in LOCKSTEP:

  1. every contract's analysis is constructed (plugins, hooks, world state)
     but deferred (``SymExecWrapper(defer_exec=True)``);
  2. per transaction round, every live analysis seeds its work list
     (``seed_message_call``) and the combined seed set — one code identity
     per contract — executes as ONE wide multi-code frontier batch
     (``frontier.engine.drain_lasers``): the corpus is the batch axis;
  3. each analysis then drains its residual work list through its own host
     engine (parked paths, frontier-ineligible states) and closes the round
     (plugin signals, open-state reseeding) exactly as ``LaserEVM.
     _execute_transactions`` does (core/svm.py:173-219);
  4. issues are grouped per contract by the distinct address each analysis
     ran at.

Semantics per contract are unchanged — the frontier parks anything it
cannot run and each laser's host engine finishes it — only the scheduling
across contracts differs.

``run_cooperative_batch`` is the long-lived-service entry point layered on
the same lockstep core: per-job fault isolation (one tenant's exception or
solver blow-up fails only that job's result, the rest of the batch
completes), per-request frontier segment tagging for trace correlation, and
per-job issue attribution that hands each job its error alongside its
issues.  ``analyze_cooperative`` keeps the original batch-tool contract
(exceptions propagate, two-tuple return).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from mythril_tpu.support.support_args import args
from mythril_tpu.support.time_handler import time_handler

log = logging.getLogger(__name__)

#: default spacing of per-contract analysis addresses (issues group by address)
BASE_ADDRESS = 0x0901D12E


def analyze_cooperative(
    jobs: Sequence[Tuple[str, bytes]],
    transaction_count: int = 2,
    modules: Optional[List[str]] = None,
    strategy: str = "bfs",
    execution_timeout: int = 60,
    base_address: int = BASE_ADDRESS,
    caps=None,
):
    """Analyze ``jobs`` (name, runtime bytecode) cooperatively.

    Returns ``(issues_by_name, total_states)``.  Every contract gets its own
    laser/plugins/hooks at a distinct address; recall semantics match
    sequential per-contract analysis (differentially tested in
    tests/analysis/test_cooperative.py).
    """
    issues_by_name, errors_by_name, total_states = run_cooperative_batch(
        jobs,
        transaction_count=transaction_count,
        modules=modules,
        strategy=strategy,
        execution_timeout=execution_timeout,
        base_address=base_address,
        caps=caps,
        isolate_errors=False,
    )
    assert not errors_by_name  # isolate_errors=False re-raises instead
    return issues_by_name, total_states


def run_cooperative_batch(
    jobs: Sequence[Tuple[str, bytes]],
    transaction_count: int = 2,
    modules: Optional[List[str]] = None,
    strategy: str = "bfs",
    execution_timeout: int = 60,
    base_address: int = BASE_ADDRESS,
    caps=None,
    isolate_errors: bool = True,
    request_tags: Optional[Sequence[str]] = None,
    request_flow_cb=None,
) -> Tuple[Dict[str, List], Dict[str, str], int]:
    """Lockstep-analyze ``jobs`` with per-job fault isolation.

    Returns ``(issues_by_name, errors_by_name, total_states)``.  A job whose
    construction, seeding, host continuation or finalization raises lands in
    ``errors_by_name`` (name -> one-line description) and drops out of later
    rounds; every other job runs to completion untouched — the multi-tenant
    isolation contract of the analysis service.  With
    ``isolate_errors=False`` the first failure propagates (the original
    ``analyze_cooperative`` behavior).

    ``request_tags`` (parallel to ``jobs``) label this batch's frontier
    segments so a shared wide device segment is attributable to the requests
    riding it (``frontier.segment`` spans carry ``requests=...``).
    ``request_flow_cb`` (a zero-arg callable, or None) is handed to the
    frontier and invoked once inside the first segment span actually
    dispatched — the service's trace-flow join point (see
    ``frontier.engine.drain_lasers``).
    """
    from mythril_tpu.analysis.security import retrieve_callback_issues
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.core.transaction import symbolic as sym_tx
    from mythril_tpu.frontier.engine import drain_lasers, reset_isolation_gauges
    from mythril_tpu.smt.solver import check_satisfiable_batch

    reset_isolation_gauges()
    errors_by_name: Dict[str, str] = {}

    def _fail(name: str, stage: str, exc: BaseException) -> None:
        if not isolate_errors:
            raise exc
        log.warning("job %r failed during %s: %s", name, stage, exc,
                    exc_info=True)
        errors_by_name.setdefault(name, f"{stage}: {exc!r}")

    addresses = [base_address + 0x10000 * i for i in range(len(jobs))]
    # --coverage-target needs a live coverage feed to measure the bar
    # against: the device frontier merges its visited planes into the
    # ledger, but the host path only feeds it through the instruction
    # coverage plugin — enable it when a bar is set and the frontier is
    # off, or the stop verdict could never latch
    host_coverage = bool(getattr(args, "coverage_target", None)) \
        and not bool(args.frontier)
    wrappers: List[Tuple[str, int, object]] = []  # (name, addr, wrapper)
    for (name, code), addr in zip(jobs, addresses):
        try:
            w = SymExecWrapper(
                code,
                address=addr,
                strategy=strategy,
                transaction_count=transaction_count,
                execution_timeout=execution_timeout,
                modules=modules,
                defer_exec=True,
                enable_coverage_strategy=host_coverage,
            )
        except Exception as e:
            _fail(name, "construction", e)
            continue
        wrappers.append((name, addr, w))

    # the global wall-clock budget covers the whole batch: the lockstep
    # rounds interleave contracts, so per-contract budgets do not partition
    time_handler.start_execution(execution_timeout * max(1, len(jobs)))
    t0 = time.time()
    for _name, _addr, w in wrappers:
        w.laser._fire("start_sym_exec")
        w.laser.time = t0
        w.laser.open_states = [w.deferred_world_state]
        w.laser.executed_transactions = True

    use_frontier = bool(args.frontier)
    # pin ONE segment-program bucket for the whole sweep: later rounds see
    # fewer live codes, and a shrunken bucket would trigger a fresh XLA
    # compile mid-run (measured at ~17s on the tunneled chip)
    bucket_floor = None
    if use_frontier and wrappers:
        from mythril_tpu.frontier.code import bucket_hint, bucket_hint_classes

        lists = [
            w.deferred_world_state[addr].code.instruction_list
            for _name, addr, w in wrappers
        ]
        if args.code_paging:
            # per-class floors: each size class keeps its own pinned
            # program, so a creation-heavy outlier no longer inflates the
            # floor every small code compiles (and pays pad for)
            bucket_floor = bucket_hint_classes(lists)
        else:
            bucket_floor = bucket_hint(lists)
    failed: set = set()
    for round_idx in range(transaction_count):
        live = []
        for name, addr, w in wrappers:
            if name in failed:
                continue
            laser = w.laser
            if not laser.open_states:
                continue
            try:
                # batched open-state prune (core/svm.py:186-197)
                if not args.sparse_pruning:
                    flags = check_satisfiable_batch(
                        [s.constraints.get_all_raw() for s in laser.open_states]
                    )
                    laser.open_states = [
                        s for s, ok in zip(laser.open_states, flags) if ok
                    ]
                if not laser.open_states:
                    continue
                laser._fire("start_sym_trans")
                sym_tx.seed_message_call(laser, addr)
            except Exception as e:
                _fail(name, f"seeding round {round_idx}", e)
                failed.add(name)
                laser.open_states = []
                continue
            live.append((name, w))
        if not live:
            break
        log.info(
            "cooperative round %d: %d live contracts, %d seeds",
            round_idx,
            len(live),
            sum(len(w.laser.work_list) for _n, w in live),
        )
        if use_frontier:
            # the whole corpus round as one wide multi-code segment batch
            try:
                drain_lasers(
                    [w.laser for _n, w in live], caps=caps,
                    bucket_floor=bucket_floor,
                    tags=request_tags,
                    flow_cb=request_flow_cb,
                )
            except Exception as e:  # graceful degradation, never lose a run
                log.warning(
                    "cooperative frontier failed; host engines continue: %s",
                    e, exc_info=True,
                )
        for name, w in live:
            # host continuation: parked paths + frontier-ineligible states.
            # A tenant whose host engine blows up (solver exception, plugin
            # bug) fails ALONE: its work list is abandoned, everyone else's
            # round closes normally.
            try:
                w.laser.exec()
                w.laser._fire("stop_sym_trans")
            except Exception as e:
                _fail(name, f"host continuation round {round_idx}", e)
                failed.add(name)
                w.laser.open_states = []
                w.laser.work_list.clear()

    benchmark_base = args.benchmark_path
    try:
        for n, (name, _addr, w) in enumerate(wrappers):
            try:
                w.laser._fire("stop_sym_exec")
                if benchmark_base and len(wrappers) > 1:
                    # one series file per contract (same convention as
                    # facade/mythril_analyzer.py) instead of silent overwrites
                    args.benchmark_path = f"{benchmark_base}.{n}"
                w.finalize()
            except Exception as e:
                _fail(name, "finalization", e)
                failed.add(name)
    finally:
        args.benchmark_path = benchmark_base

    # callback issues accumulated across ALL contracts: group by the code
    # hash every issue carries (Issue.bytecode_hash; Issue.address is the
    # instruction address, not the account).  Identical bytecode under two
    # names shares its issues — the per-code issue cache (module/base.py:49)
    # deduplicates detection, so both names must see the findings.
    from mythril_tpu.support.support_utils import get_code_hash

    by_hash: Dict[str, List] = {}
    for issue in retrieve_callback_issues(modules):
        by_hash.setdefault(issue.bytecode_hash, []).append(issue)
    issues_by_name = {
        name: by_hash.get(get_code_hash(code), [])
        for (name, code) in jobs
        if name not in errors_by_name
    }
    total_states = sum(w.laser.total_states for _n, _a, w in wrappers)
    return issues_by_name, errors_by_name, total_states
