"""Control-flow-graph bookkeeping: nodes, edges, jump types.

Reference parity: mythril/laser/ethereum/cfg.py:12-116.
"""

from __future__ import annotations

from enum import Enum
from typing import Dict, List, Optional

gbl_next_uid = [0]


class JumpType(Enum):
    CONDITIONAL = 1
    UNCONDITIONAL = 2
    CALL = 3
    RETURN = 4
    Transaction = 5


class NodeFlags:
    FUNC_ENTRY = 1
    CALL_RETURN = 2


class Node:
    def __init__(self, contract_name: str, start_addr: int = 0, constraints=None, function_name: str = "unknown"):
        from mythril_tpu.core.state.constraints import Constraints

        self.contract_name = contract_name
        self.start_addr = start_addr
        self.constraints = constraints if constraints is not None else Constraints()
        self.function_name = function_name
        self.flags = 0
        self.states: List = []
        gbl_next_uid[0] += 1
        self.uid = gbl_next_uid[0]

    def get_dict(self) -> Dict:
        return {
            "contract_name": self.contract_name,
            "start_addr": self.start_addr,
            "function_name": self.function_name,
            "uid": self.uid,
            "flags": self.flags,
            "num_states": len(self.states),
        }

    def __repr__(self):
        return f"<Node {self.uid} {self.function_name}@{self.start_addr}>"


class Edge:
    def __init__(
        self,
        node_from: int,
        node_to: int,
        edge_type: JumpType = JumpType.UNCONDITIONAL,
        condition=None,
    ):
        self.node_from = node_from
        self.node_to = node_to
        self.type = edge_type
        self.condition = condition

    def as_dict(self) -> Dict:
        return {"from": self.node_from, "to": self.node_to, "type": self.type.name}

    def __repr__(self):
        return f"<Edge {self.node_from} -> {self.node_to} ({self.type.name})>"
