"""Detector gating: skip modules statically proven irrelevant.

Two over-approximate gates, both declared by the module itself
(analysis/module/base.py):

* occurrence gate — ``static_required_ops``: the module can only raise an
  issue when at least one of these opcodes occurs on a reachable
  instruction.  None disables the gate (custom/undeclared modules are
  never skipped).
* taint gate — ``static_taint_sources``/``static_taint_sinks``: the
  module only raises when a source's value influences a sink; skipped
  when no reachable source bit may_reach any declared sink.

The gate sees the contract's WHOLE static code set (creation + runtime)
through a GateView: a bit escalated in one code (it hit a global channel,
e.g. a constructor SSTORE) may reach sinks in every other code.  When any
executable code is statically unknown — dynloader active, creation-only
inputs, checkpoint resume — no view is built and nothing is skipped.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from mythril_tpu.staticpass.summary import (
    StaticSummary,
    record_summary_metrics,
    summary_for_code,
)

log = logging.getLogger(__name__)


class GateView:
    """Union view over every code object a contract can execute."""

    def __init__(self, summaries: List[StaticSummary], contract_name: str = "?"):
        self.summaries = summaries
        self.contract_name = contract_name
        self.reachable_opcodes = frozenset().union(
            *(s.reachable_opcodes for s in summaries)
        ) if summaries else frozenset()
        self.skipped_modules: List[str] = []

    def taint_reach(self, bit: int) -> frozenset:
        reached = frozenset().union(
            *(s.taint_reach(bit) for s in self.summaries)
        ) if self.summaries else frozenset()
        if any(bit in s.escalated_bits for s in self.summaries):
            # an escalated bit crosses code boundaries (storage persists
            # between the constructor and every runtime tx)
            reached |= self.reachable_opcodes
        return reached


def module_relevant(module, view: GateView) -> bool:
    """Can ``module`` possibly raise an issue on this contract?"""
    required = getattr(module, "static_required_ops", None)
    if required is not None and not (view.reachable_opcodes & required):
        return False
    sources = getattr(module, "static_taint_sources", None)
    sinks = getattr(module, "static_taint_sinks", None)
    if sources and sinks:
        return any(
            src_op in view.reachable_opcodes and (view.taint_reach(bit) & sinks)
            for src_op, bit in sources.items()
        )
    return True


def filter_modules(modules: List, view: Optional[GateView]) -> Tuple[List, List]:
    """(kept, skipped) — identity when no view is available."""
    if view is None:
        return modules, []
    kept, skipped = [], []
    for m in modules:
        (kept if module_relevant(m, view) else skipped).append(m)
    if skipped:
        view.skipped_modules = sorted(type(m).__name__ for m in skipped)
        log.info(
            "static pass: skipping statically irrelevant modules for %s: %s",
            view.contract_name, ", ".join(view.skipped_modules),
        )
    return kept, skipped


def gate_view_for_contract(contract, dynloader=None,
                           resume_from=None) -> Optional[GateView]:
    """Build the gating view for one contract, or None when the full
    executable code set is not statically known (then nothing is gated)."""
    from mythril_tpu.support.support_args import args

    if not getattr(args, "staticpass", True):
        return None
    if resume_from:
        return None  # restored states may sit mid-flow past a gate point
    if dynloader is not None and getattr(dynloader, "active", False):
        return None  # on-chain code loading: other bytecode can run
    try:
        summaries: List[StaticSummary] = []
        if isinstance(contract, (bytes, bytearray)):
            from mythril_tpu.frontend.disassembler import Disassembly

            summaries.append(summary_for_code(Disassembly(bytes(contract))))
        else:
            runtime = getattr(contract, "disassembly", None)
            creation = getattr(contract, "creation_disassembly", None)
            if creation is not None and runtime is None:
                # creation-only input: the deployed runtime code is the
                # creation tx's return value, not statically available
                return None
            if runtime is not None:
                summaries.append(summary_for_code(runtime))
            if creation is not None:
                summaries.append(summary_for_code(creation, is_creation=True))
        if not summaries or any(s is None for s in summaries):
            return None
        for s in summaries:
            record_summary_metrics(s)
        view = GateView(
            summaries, contract_name=getattr(contract, "name", "Unknown")
        )
        from mythril_tpu.staticpass import report as sp_report

        sp_report.record_view(view)
        return view
    except Exception as e:  # never fatal: analysis continues ungated
        log.warning("static gate unavailable for this contract: %s", e)
        return None
