"""Admission scheduling policy: tenant quotas, priority aging, shedding.

PR 9's tenant accounting made per-tenant load visible; this module makes
it actionable.  The policy runs entirely inside the admission plane —
workers never see it — and has three independent levers:

* **Tenant quota** (``max_pending_per_tenant``): a tenant may hold at
  most N *new* pending flights (dedup subscriptions are free — they add
  no work).  The N+1st submission is rejected with a one-line error the
  submitter sees immediately; nothing is queued.  This bounds how much
  of the admission queue one hot tenant can own, which is what keeps the
  interactive tier's queue-wait flat under a tenant flood.

* **Load shedding** (``shed_queue_depth``): when the pending queue is
  this deep, *batch-tier* submissions are refused outright (shed), while
  interactive submissions still queue — a saturated service degrades by
  dropping bulk work, not by stretching interactive p95s.  Shedding is
  visible: ``service.shed_total`` counts every refusal.

* **Priority aging** (``age_priority_s``): interactive flights jump the
  queue; a batch flight that has waited ``age_priority_s`` is promoted
  to the same priority class, so a continuous interactive stream ages
  batch work forward instead of starving it forever.  Within a class,
  FIFO by first submission.

``AdmissionRejected`` is a ``RuntimeError`` so every existing transport
path (server error event, client exception) reports it unchanged.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

__all__ = ["AdmissionRejected", "SchedulerPolicy"]


class AdmissionRejected(RuntimeError):
    """Submission refused by admission policy (quota or load shed)."""

    def __init__(self, reason: str, kind: str = "rejected"):
        super().__init__(reason)
        self.kind = kind  # "quota" | "shed"


@dataclass(frozen=True)
class SchedulerPolicy:
    #: max new pending flights one tenant may hold (0 = unlimited)
    max_pending_per_tenant: int = 0
    #: pending-queue depth at which batch-tier submissions are shed
    #: (0 = never shed)
    shed_queue_depth: int = 0
    #: batch flights waiting at least this long are promoted to
    #: interactive-class priority (<= 0 disables aging)
    age_priority_s: float = 30.0

    @property
    def active(self) -> bool:
        return bool(
            self.max_pending_per_tenant
            or self.shed_queue_depth
            or self.age_priority_s > 0
        )

    def priority_class(self, interactive: bool, created_at: float,
                       now: Optional[float] = None) -> int:
        """0 = dispatch-first class, 1 = normal batch backlog."""
        if interactive:
            return 0
        if self.age_priority_s > 0:
            now = time.time() if now is None else now
            if now - created_at >= self.age_priority_s:
                return 0
        return 1
