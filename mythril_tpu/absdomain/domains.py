"""Vectorized transfer functions over two sound abstract domains.

Unsigned intervals
    ``[lo, hi]`` per (node, row) in float64 with DIRECTED rounding:
    every arithmetic result is widened one ulp outward (``np.nextafter``),
    and integer constants that float64 cannot represent are rounded
    outward at pack time.  The invariant is only ever ``lo <= v <= hi``
    for every concrete model value ``v`` — the domain trades precision
    for a dense dtype, never soundness.  Exactness is NOT assumed
    anywhere: equality decisions come from the known-bits domain.

Known bits
    ``(km, kv)`` per (node, row): 16 uint32 limbs each, bit ``j`` of the
    value is known iff bit ``j`` of ``km`` is set, in which case it
    equals bit ``j`` of ``kv``.  Invariants: ``kv & ~km == 0`` and bits
    at or above the node's width are always known zero.  This domain is
    exact integer arithmetic — it decides equalities/comparisons between
    fully-pinned 256-bit values that float64 intervals cannot.

Both domains' kernels are written against an ``xp`` array namespace so
the identical code runs under host numpy and under ``jax.numpy`` inside
the device interpreter (``absdomain/device.py``).  Known-bits kernels use
only uint32/int32/bool — sound without JAX x64 — which is what makes the
known-bits pass device-residable at all; the interval pass needs float64
and stays on host numpy (vectorized over the whole batch).

A transformer may always return a coarser element (top); it must never
exclude a value some concrete model can take.  The differential fuzz test
(tests/absdomain/test_fuzz_differential.py) checks exactly that property
against ``smt/concrete_eval.evaluate``.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Tuple

import numpy as np

from mythril_tpu.native.bitblast import (
    OP_CONST, OP_VAR, OP_EQ, OP_AND, OP_OR, OP_NOT, OP_XOR, OP_ITE,
    OP_ADD, OP_SUB, OP_MUL, OP_UDIV, OP_UREM, OP_SDIV, OP_SREM,
    OP_BAND, OP_BOR, OP_BXOR, OP_BNOT, OP_NEG, OP_SHL, OP_LSHR, OP_ASHR,
    OP_CONCAT, OP_EXTRACT, OP_ZEXT, OP_SEXT, OP_ULT, OP_ULE, OP_SLT, OP_SLE,
)

from mythril_tpu.absdomain.tape import LIMBS, U32, PackedBatch

_ALL = 0xFFFFFFFF
_INF = np.inf


class NodeParams(NamedTuple):
    """Per-node scalars handed to every kernel (host ints / traced 0-d)."""

    w: object        # node width in bits
    x0: object       # extract hi / const offset
    x1: object       # extract lo / const nbytes
    wm: object       # [LIMBS] width mask
    cl: object       # [LIMBS] OP_CONST payload limbs
    wa: object       # width of operand a0 (0 when absent)
    wb: object       # width of operand a1 (0 when absent)


# ---------------------------------------------------------------------------
# Known-bits kernels (xp-agnostic: numpy or jax.numpy)
# ---------------------------------------------------------------------------


def _u32(xp, x):
    return xp.asarray(x, dtype=xp.uint32)


def _fully_known(xp, km):
    return (km == _u32(xp, _ALL)).all(axis=-1)


def _bool_out(xp, wm, like, decided, value):
    """Encode a bool node: bit0 known iff ``decided``, then equal ``value``."""
    z = xp.zeros_like(like)
    km = (z + (~wm)) | xp.where(decided[:, None], z + wm, z)
    kv = xp.where((decided & value)[:, None], z + wm, z)
    return km, kv


def _kb_top(xp, p, A, B, C):
    z = xp.zeros_like(A[0])
    return z + (~p.wm), z


def _kb_const(xp, p, A, B, C):
    z = xp.zeros_like(A[0])
    return z + _u32(xp, _ALL), z + p.cl


def _kb_band(xp, p, A, B, C):
    ka, va = A
    kb, vb = B
    km = (ka & kb) | (ka & ~va) | (kb & ~vb)
    return km, va & vb & km


def _kb_bor(xp, p, A, B, C):
    ka, va = A
    kb, vb = B
    km = (ka & kb) | (ka & va) | (kb & vb)
    return km, (va | vb) & km


def _kb_bxor(xp, p, A, B, C):
    ka, va = A
    kb, vb = B
    km = ka & kb
    return km, (va ^ vb) & km


def _kb_bnot(xp, p, A, B, C):
    ka, va = A
    return ka, (~va) & ka & p.wm


def _ripple_add(xp, va, vb, carry_in):
    """512-bit add over the limb axis without 64-bit intermediates."""
    carry = xp.zeros_like(va[..., 0]) + _u32(xp, carry_in)
    out = []
    for i in range(LIMBS):
        t = va[..., i] + vb[..., i]
        c1 = t < va[..., i]
        s = t + carry
        c2 = s < t
        out.append(s)
        carry = (c1 | c2).astype(xp.uint32)
    return xp.stack(out, axis=-1)


def _kb_fullknown(xp, p, A, B, value):
    """Known exactly where both operands are fully pinned, else top."""
    fully = (_fully_known(xp, A[0]) & _fully_known(xp, B[0]))[:, None]
    z = xp.zeros_like(A[0])
    km = xp.where(fully, z + _u32(xp, _ALL), z + (~p.wm))
    kv = xp.where(fully, value & p.wm, z)
    return km, kv & km


def _bitlen32(xp, v):
    """Per-limb bit length (0..32) via smear + SWAR popcount, uint32-only."""
    v = v | (v >> 1)
    v = v | (v >> 2)
    v = v | (v >> 4)
    v = v | (v >> 8)
    v = v | (v >> 16)
    v = v - ((v >> 1) & _u32(xp, 0x55555555))
    v = (v & _u32(xp, 0x33333333)) + ((v >> 2) & _u32(xp, 0x33333333))
    v = (v + (v >> 4)) & _u32(xp, 0x0F0F0F0F)
    v = (v * _u32(xp, 0x01010101)) >> 24
    return v.astype(xp.int32)


def _pbits(xp, km, kv):
    """[rows] EXACT max bit-length of the value: the element guarantees
    ``v <= 2**_pbits - 1``.  Bits that are known zero cannot contribute."""
    x = ~(km & ~kv)  # possibly-one bits (zero at/above width by invariant)
    bl = _bitlen32(xp, x)
    li = xp.arange(LIMBS, dtype=xp.int32) * 32
    per = xp.where(x != 0, bl + li, xp.zeros_like(bl))
    return per.max(axis=-1)


def _mask_ge(xp, n, like):
    """[rows, LIMBS] mask of the bits at positions >= n (n per row)."""
    return ~_mask_below(xp, n, like)


def _kb_add(xp, p, A, B, C):
    km, kv = _kb_fullknown(xp, p, A, B, _ripple_add(xp, A[1], B[1], 0))
    # leading zeros: a + b <= 2^pa + 2^pb - 2 < 2^(max(pa,pb)+1); when that
    # threshold exceeds the width the claim only covers bits already known
    # zero, so wrap-around cannot be mis-modeled
    thr = xp.maximum(_pbits(xp, *A), _pbits(xp, *B)) + 1
    return km | _mask_ge(xp, thr, A[0]), kv


def _kb_sub(xp, p, A, B, C):
    return _kb_fullknown(xp, p, A, B, _ripple_add(xp, A[1], ~B[1], 1))


def _kb_mul(xp, p, A, B, C):
    """Exact leading-zero propagation: with a <= 2^pa - 1 and b <= 2^pb - 1,
    ab <= (2^pa - 1)(2^pb - 1).  In particular ab == 0 when either factor
    is 0, ab <= 2^pb - 1 when pa <= 1 (a is 0 or 1, symmetrically for b),
    and ab < 2^(pa+pb) always.  The pa <= 1 case is what recovers
    refutations like ``cnt <= 1 && cnt*value >= 2^256`` that float64
    intervals lose at the 2^w - 1 representation boundary."""
    pa = _pbits(xp, *A)
    pb = _pbits(xp, *B)
    thr = xp.where((pa == 0) | (pb == 0), 0,
                   xp.where(pa <= 1, pb,
                            xp.where(pb <= 1, pa, pa + pb)))
    z = xp.zeros_like(A[0])
    return (z + (~p.wm)) | _mask_ge(xp, thr, A[0]), z


def _kb_div_rem(xp, p, A, B, C):
    """udiv/urem never exceed the dividend (division by zero yields 0 in
    this engine's EVM semantics), so the dividend's leading zeros carry."""
    z = xp.zeros_like(A[0])
    return (z + (~p.wm)) | _mask_ge(xp, _pbits(xp, *A), A[0]), z


def _kb_neg(xp, p, A, B, C):
    z = (xp.zeros_like(A[1]), xp.zeros_like(A[1]))
    fully = _fully_known(xp, A[0])[:, None]
    val = _ripple_add(xp, ~A[1], z[1], 1)
    zz = xp.zeros_like(A[0])
    km = xp.where(fully, zz + _u32(xp, _ALL), zz + (~p.wm))
    kv = xp.where(fully, val & p.wm, zz)
    return km, kv & km


def _limb_ult(xp, va, vb):
    """Exact (a < b, a == b) from fully-known limbs, high to low."""
    lt = xp.zeros(va.shape[:-1], bool)
    eq = xp.ones(va.shape[:-1], bool)
    for i in reversed(range(LIMBS)):
        lt = lt | (eq & (va[..., i] < vb[..., i]))
        eq = eq & (va[..., i] == vb[..., i])
    return lt, eq


def _kb_eq(xp, p, A, B, C):
    ka, va = A
    kb, vb = B
    conflict = ((ka & kb & (va ^ vb)) != 0).any(axis=-1)
    both = _fully_known(xp, ka) & _fully_known(xp, kb)
    must_true = both & ~conflict
    return _bool_out(xp, p.wm, ka, conflict | must_true, must_true)


def _kb_ult(xp, p, A, B, C):
    both = _fully_known(xp, A[0]) & _fully_known(xp, B[0])
    lt, _eq = _limb_ult(xp, A[1], B[1])
    return _bool_out(xp, p.wm, A[0], both, lt)


def _kb_ule(xp, p, A, B, C):
    both = _fully_known(xp, A[0]) & _fully_known(xp, B[0])
    lt, eq = _limb_ult(xp, A[1], B[1])
    return _bool_out(xp, p.wm, A[0], both, lt | eq)


def _kb_ite(xp, p, A, B, C):
    ck = (A[0][..., 0] & 1) != 0
    cv = (A[1][..., 0] & 1) != 0
    kmj = B[0] & C[0] & ~(B[1] ^ C[1])
    kvj = B[1] & kmj
    then = (ck & cv)[:, None]
    els = (ck & ~cv)[:, None]
    km = xp.where(then, B[0], xp.where(els, C[0], kmj))
    kv = xp.where(then, B[1], xp.where(els, C[1], kvj))
    return km, kv


def _mask_below(xp, n, like):
    """Mask of bits strictly below ``n`` (scalar or per-row array),
    broadcast against ``like``."""
    base = xp.arange(LIMBS, dtype=xp.int32) * 32
    n_arr = xp.asarray(n, dtype=xp.int32)
    k = xp.clip(n_arr[..., None] - base, 0, 32)
    one = _u32(xp, 1)
    partial = (one << (k.astype(xp.uint32) & _u32(xp, 31))) - one
    m = xp.where(k >= 32, _u32(xp, _ALL), partial)
    return xp.zeros_like(like) + m


def _shift_amount(xp, B):
    """(fully-known?, clamped shift) — any amount >= 1024 acts as 1023."""
    known = _fully_known(xp, B[0])
    high = (B[1][..., 1:] != 0).any(axis=-1)
    s = xp.where(high, _u32(xp, 1023), B[1][..., 0])
    return known, xp.minimum(s, _u32(xp, 1023))


def _limb_lshr(xp, v, s):
    ls = (s >> _u32(xp, 5)).astype(xp.int32)
    bs = (s & _u32(xp, 31))[:, None]
    idx = xp.arange(LIMBS, dtype=xp.int32)[None, :] + ls[:, None]
    z = xp.zeros_like(v)
    v0 = xp.where(idx < LIMBS,
                  xp.take_along_axis(v, xp.minimum(idx, LIMBS - 1), axis=-1),
                  z)
    idx1 = idx + 1
    v1 = xp.where(idx1 < LIMBS,
                  xp.take_along_axis(v, xp.minimum(idx1, LIMBS - 1), axis=-1),
                  z)
    back = (_u32(xp, 32) - bs) & _u32(xp, 31)
    return (v0 >> bs) | xp.where(bs > 0, v1 << back, z)


def _limb_shl(xp, v, s):
    ls = (s >> _u32(xp, 5)).astype(xp.int32)
    bs = (s & _u32(xp, 31))[:, None]
    idx = xp.arange(LIMBS, dtype=xp.int32)[None, :] - ls[:, None]
    z = xp.zeros_like(v)
    v0 = xp.where(idx >= 0,
                  xp.take_along_axis(v, xp.clip(idx, 0, LIMBS - 1), axis=-1),
                  z)
    idx1 = idx - 1
    v1 = xp.where(idx1 >= 0,
                  xp.take_along_axis(v, xp.clip(idx1, 0, LIMBS - 1), axis=-1),
                  z)
    back = (_u32(xp, 32) - bs) & _u32(xp, 31)
    return (v0 << bs) | xp.where(bs > 0, v1 >> back, z)


def _kb_shl(xp, p, A, B, C):
    known, s = _shift_amount(xp, B)
    zero = known & (s.astype(xp.int32) >= xp.asarray(p.w, dtype=xp.int32))
    u_s = _limb_shl(xp, ~A[0], s)
    km_s = (~u_s) | ~p.wm
    kv_s = _limb_shl(xp, A[1], s) & p.wm & km_s
    z = xp.zeros_like(A[0])
    km = xp.where(zero[:, None], z + _u32(xp, _ALL),
                  xp.where(known[:, None], km_s, z + (~p.wm)))
    kv = xp.where(zero[:, None], z, xp.where(known[:, None], kv_s, z))
    return km, kv


def _lshr_pair(xp, p, A, known, s):
    zero = known & (s.astype(xp.int32) >= xp.asarray(p.w, dtype=xp.int32))
    u_s = _limb_lshr(xp, ~A[0], s)
    km_s = (~u_s) | ~p.wm
    kv_s = _limb_lshr(xp, A[1], s) & p.wm & km_s
    z = xp.zeros_like(A[0])
    km = xp.where(zero[:, None], z + _u32(xp, _ALL),
                  xp.where(known[:, None], km_s, z + (~p.wm)))
    kv = xp.where(zero[:, None], z, xp.where(known[:, None], kv_s, z))
    return km, kv


def _kb_lshr(xp, p, A, B, C):
    known, s = _shift_amount(xp, B)
    return _lshr_pair(xp, p, A, known, s)


def _bit_at(xp, arr, pos):
    li = xp.asarray(pos, dtype=xp.int32) >> 5
    bi = (xp.asarray(pos, dtype=xp.uint32)) & _u32(xp, 31)
    limb = xp.take(arr, li, axis=-1)
    return ((limb >> bi) & 1) != 0


def _kb_ashr(xp, p, A, B, C):
    # sound only when the sign bit is provably 0 (then ashr == lshr,
    # including the clamp-at-w-1 semantics: a >> (w-1) == 0 for sign-0 a)
    sign_known_zero = (_bit_at(xp, A[0], p.w - 1)
                       & ~_bit_at(xp, A[1], p.w - 1))
    known, s = _shift_amount(xp, B)
    km_s, kv_s = _lshr_pair(xp, p, A, known, s)
    ok = sign_known_zero[:, None]
    z = xp.zeros_like(A[0])
    return xp.where(ok, km_s, z + (~p.wm)), xp.where(ok, kv_s, z)


def _kb_concat(xp, p, A, B, C):
    low = _mask_below(xp, p.wb, A[0])
    s = xp.zeros(A[0].shape[:-1], xp.uint32) + _u32(xp, p.wb)
    u_a = _limb_shl(xp, ~A[0], s)
    km = ((~u_a) & ~low) | (B[0] & low) | ~p.wm
    kv = ((_limb_shl(xp, A[1], s) & ~low) | (B[1] & low)) & p.wm & km
    return km, kv


def _kb_extract(xp, p, A, B, C):
    s = xp.zeros(A[0].shape[:-1], xp.uint32) + _u32(xp, p.x1)
    u_s = _limb_lshr(xp, ~A[0], s)
    km = (~u_s) | ~p.wm
    kv = _limb_lshr(xp, A[1], s) & p.wm & km
    return km, kv


def _kb_zext(xp, p, A, B, C):
    return A  # bits above the old width are already known zero


def _kb_sext(xp, p, A, B, C):
    below = _mask_below(xp, p.wa, A[0])
    sk = _bit_at(xp, A[0], p.wa - 1)[:, None]
    sv = _bit_at(xp, A[1], p.wa - 1)[:, None]
    ext = p.wm & ~below
    z = xp.zeros_like(A[0])
    km = (A[0] & below) | (~p.wm) | xp.where(sk, z + ext, z)
    kv = ((A[1] & below) | xp.where(sk & sv, z + ext, z)) & km
    return km, kv


KB_KERNELS = {
    OP_CONST: _kb_const,
    OP_VAR: _kb_top,
    OP_EQ: _kb_eq,
    OP_AND: _kb_band,
    OP_OR: _kb_bor,
    OP_NOT: _kb_bnot,
    OP_XOR: _kb_bxor,
    OP_ITE: _kb_ite,
    OP_ADD: _kb_add,
    OP_SUB: _kb_sub,
    OP_MUL: _kb_mul,
    OP_UDIV: _kb_div_rem,
    OP_UREM: _kb_div_rem,
    OP_SDIV: _kb_top,
    OP_SREM: _kb_top,
    OP_BAND: _kb_band,
    OP_BOR: _kb_bor,
    OP_BXOR: _kb_bxor,
    OP_BNOT: _kb_bnot,
    OP_NEG: _kb_neg,
    OP_SHL: _kb_shl,
    OP_LSHR: _kb_lshr,
    OP_ASHR: _kb_ashr,
    OP_CONCAT: _kb_concat,
    OP_EXTRACT: _kb_extract,
    OP_ZEXT: _kb_zext,
    OP_SEXT: _kb_sext,
    OP_ULT: _kb_ult,
    OP_ULE: _kb_ule,
    OP_SLT: _kb_top,
    OP_SLE: _kb_top,
}


def node_params(pack: PackedBatch, i: int) -> NodeParams:
    a0, a1 = int(pack.a0[i]), int(pack.a1[i])
    return NodeParams(
        w=int(pack.w[i]),
        x0=int(pack.x0[i]),
        x1=int(pack.x1[i]),
        wm=pack.wm[i],
        cl=pack.c_limbs[i],
        wa=int(pack.w[a0]) if a0 >= 0 else 0,
        wb=int(pack.w[a1]) if a1 >= 0 else 0,
    )


def eval_kb_host(pack: PackedBatch) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Numpy known-bits pass: one loop over nodes, vectorized over rows."""
    n, r = pack.n_nodes, pack.n_rows
    km = np.zeros((n, r, LIMBS), U32)
    kv = np.zeros((n, r, LIMBS), U32)
    refuted = np.zeros(r, bool)
    dummy = (np.zeros((r, LIMBS), U32), np.zeros((r, LIMBS), U32))

    def child(j):
        return (km[j], kv[j]) if j >= 0 else dummy

    for i in range(n):
        p = node_params(pack, i)
        fn = KB_KERNELS.get(int(pack.op[i]), _kb_top)
        k, v = fn(np, p, child(int(pack.a0[i])), child(int(pack.a1[i])),
                  child(int(pack.a2[i])))
        ov = pack.overrides.get(i)
        if ov is not None:
            _olo, _ohi, okm, okv = ov
            refuted |= ((k & okm & (v ^ okv)) != 0).any(axis=-1)
            k = k | okm
            v = (v | okv) & k
        km[i], kv[i] = k, v
    return km, kv, refuted


# ---------------------------------------------------------------------------
# Interval pass (host-only: needs float64)
# ---------------------------------------------------------------------------


def _up(x):
    return np.nextafter(x, _INF)


def _dn(x):
    return np.nextafter(x, -_INF)


_WB_CACHE: Dict[int, Tuple[float, float, float, float]] = {}


def _wbounds(w: int) -> Tuple[float, float, float, float]:
    """(under(2^w-1), over(2^w-1), 2^w exact, 2^(w-1) exact) for width w."""
    got = _WB_CACHE.get(w)
    if got is None:
        full = (1 << w) - 1
        f = float(full)
        fu = f if int(f) <= full else float(np.nextafter(f, -_INF))
        fo = f if int(f) >= full else float(np.nextafter(f, _INF))
        got = (fu, fo, float(1 << w), float(1 << (w - 1)) if w else 0.5)
        _WB_CACHE[w] = got
    return got


def eval_iv_host(pack: PackedBatch) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Numpy interval pass: one loop over nodes, vectorized over rows."""
    n, r = pack.n_nodes, pack.n_rows
    lo = np.zeros((n, r), np.float64)
    hi = np.zeros((n, r), np.float64)
    refuted = np.zeros(r, bool)
    W = np.where

    # top*top at 512 bits overflows float64 to inf; the wrap guards
    # (`ph <= fu`) treat that as "widen to full range", which is sound —
    # silence the transient overflow/invalid warnings
    with np.errstate(all="ignore"):
        return _eval_iv_loop(pack, lo, hi, refuted, W)


def _eval_iv_loop(pack, lo, hi, refuted, W):
    n, r = pack.n_nodes, pack.n_rows
    for i in range(n):
        op = int(pack.op[i])
        w = int(pack.w[i])
        a0, a1, a2 = int(pack.a0[i]), int(pack.a1[i]), int(pack.a2[i])
        fu, fo, p2, half = _wbounds(w)
        if op == OP_CONST:
            l_, h_ = np.full(r, pack.c_lo[i]), np.full(r, pack.c_hi[i])
        elif op == OP_VAR:
            l_, h_ = np.zeros(r), np.full(r, fo)
        else:
            la, ha = lo[a0], hi[a0]
            lb = lo[a1] if a1 >= 0 else None
            hb = hi[a1] if a1 >= 0 else None
            if op == OP_EQ:
                mf = (ha < lb) | (hb < la)
                mt = (la == ha) & (lb == hb) & (la == lb)
                l_, h_ = W(mt, 1.0, 0.0), W(mf, 0.0, 1.0)
            elif op == OP_AND:
                l_, h_ = np.minimum(la, lb), np.minimum(ha, hb)
            elif op == OP_OR:
                l_, h_ = np.maximum(la, lb), np.maximum(ha, hb)
            elif op == OP_NOT:
                l_, h_ = 1.0 - ha, 1.0 - la
            elif op == OP_XOR:
                pinned = (la == ha) & (lb == hb)
                v = ((la >= 0.5) != (lb >= 0.5)).astype(np.float64)
                l_, h_ = W(pinned, v, 0.0), W(pinned, v, 1.0)
            elif op == OP_ITE:
                lt, ht = lo[a1], hi[a1]
                le, he = lo[a2], hi[a2]
                ct, cf = la >= 1.0, ha <= 0.0
                l_ = W(ct, lt, W(cf, le, np.minimum(lt, le)))
                h_ = W(ct, ht, W(cf, he, np.maximum(ht, he)))
            elif op == OP_ADD:
                sh = _up(ha + hb)
                nw = sh <= fu
                l_, h_ = W(nw, _dn(la + lb), 0.0), W(nw, sh, fo)
            elif op == OP_SUB:
                nw = la >= hb
                l_, h_ = W(nw, _dn(la - hb), 0.0), W(nw, _up(ha - lb), fo)
            elif op == OP_MUL:
                ph = _up(ha * hb)
                nw = ph <= fu
                l_, h_ = W(nw, _dn(la * lb), 0.0), W(nw, ph, fo)
            elif op == OP_UDIV:
                l_ = np.zeros(r)
                h_ = W(lb >= 1.0, _up(ha / np.maximum(lb, 1.0)), ha)
            elif op == OP_UREM:
                l_ = np.zeros(r)
                h_ = W(lb >= 1.0, np.minimum(ha, hb), ha)
            elif op == OP_BAND:
                l_, h_ = np.zeros(r), np.minimum(ha, hb)
            elif op == OP_BOR:
                l_ = np.maximum(la, lb)
                h_ = np.minimum(fo, _up(ha + hb))
            elif op == OP_BXOR:
                l_, h_ = np.zeros(r), np.minimum(fo, _up(ha + hb))
            elif op == OP_BNOT:
                l_, h_ = _dn(fu - ha), _up(fo - la)
            elif op == OP_NEG:
                l_ = W(la >= 1.0, _dn(p2 - ha), 0.0)
                h_ = np.minimum(fo, W(ha <= 0.0, 0.0, _up(p2 - la)))
            elif op in (OP_SHL, OP_LSHR, OP_ASHR):
                sk = lb == hb  # shift amount pinned to one (exact) float
                k = np.minimum(lb, 1100.0)
                pw = np.power(2.0, k)
                big = lb >= float(w)
                if op == OP_SHL:
                    ph = _up(ha * pw)
                    nw = ph <= fu
                    l_ = W(sk, W(big, 0.0, W(nw, _dn(la * pw), 0.0)), 0.0)
                    h_ = W(sk, W(big, 0.0, W(nw, ph, fo)), fo)
                else:
                    shr_l = np.maximum(0.0, _dn(la / pw) - 1.0)
                    shr_h = np.minimum(_up(ha / pw), ha)
                    ok = sk & ((op == OP_LSHR) | (ha < half))
                    l_ = W(ok, W(big, 0.0, shr_l), 0.0)
                    h_ = W(ok, W(big, 0.0, shr_h), fo)
            elif op == OP_CONCAT:
                pwl = float(1 << int(pack.w[a1]))
                l_ = _dn(la * pwl + lb)
                h_ = _up(ha * pwl + hb)
            elif op == OP_EXTRACT:
                hi_bit, lo_bit = int(pack.x0[i]), int(pack.x1[i])
                in_range = ha < float(1 << (hi_bit + 1))
                if lo_bit == 0:
                    l_, h_ = W(in_range, la, 0.0), W(in_range, ha, fo)
                else:
                    plo = float(1 << lo_bit)
                    l_ = W(in_range, np.maximum(0.0, _dn(la / plo) - 1.0), 0.0)
                    h_ = W(in_range, _up(ha / plo), fo)
            elif op == OP_ZEXT:
                l_, h_ = la, ha
            elif op == OP_SEXT:
                in_half = _wbounds(int(pack.w[a0]))[3]
                pos = ha < in_half
                l_, h_ = W(pos, la, 0.0), W(pos, ha, fo)
            elif op == OP_ULT:
                l_, h_ = W(ha < lb, 1.0, 0.0), W(la >= hb, 0.0, 1.0)
            elif op == OP_ULE:
                l_, h_ = W(ha <= lb, 1.0, 0.0), W(la > hb, 0.0, 1.0)
            else:  # SDIV/SREM/SLT/SLE and anything unmodeled: top
                l_, h_ = np.zeros(r), np.full(r, fo)

        l_ = np.maximum(l_, 0.0)
        h_ = np.minimum(h_, fo)
        ov = pack.overrides.get(i)
        if ov is not None:
            olo, ohi, _okm, _okv = ov
            l_ = np.maximum(l_, olo)
            h_ = np.minimum(h_, ohi)
            refuted |= l_ > h_
            h_ = np.maximum(h_, l_)
        lo[i], hi[i] = l_, h_
    return lo, hi, refuted


# ---------------------------------------------------------------------------
# Verdicts
# ---------------------------------------------------------------------------

_WEIGHTS = 2.0 ** (32.0 * np.arange(LIMBS, dtype=np.float64))


def verdicts(pack: PackedBatch, lo: np.ndarray, hi: np.ndarray,
             km: np.ndarray, kv: np.ndarray,
             refuted: np.ndarray) -> np.ndarray:
    """Combine both domains into one UNSAT-proof bit per row.

    A row is refuted when (a) its harvested narrowings were contradictory,
    (b) any node's interval/known-bits elements have empty intersection, or
    (c) any of its asserted roots is must-false in either domain.
    """
    # cross-domain consistency: the kb element bounds the value from below
    # (unknown bits zero) and above (unknown bits one); 16 float adds cost
    # at most 16 ulps, widened outward before comparing
    lo_kb = (kv.astype(np.float64) * _WEIGHTS).sum(axis=-1)
    hi_bits = kv | (~km & pack.wm[:, None, :])
    hi_kb = (hi_bits.astype(np.float64) * _WEIGHTS).sum(axis=-1)
    lo_kb = lo_kb - 16.0 * np.spacing(lo_kb)
    hi_kb = hi_kb + 16.0 * np.spacing(hi_kb)
    cross = (lo_kb > hi) | (hi_kb < lo)
    out = refuted | cross.any(axis=0) | pack.row_refuted

    # exact re-check of every harvested demand: float64 cannot separate
    # 2^w - 1 from 2^w, but the known-bits element and the harvested range
    # are both exact integers, so compare them as such
    for node, entries in pack.ov_exact.items():
        wm_int = 0
        for li in range(LIMBS):
            wm_int |= int(pack.wm[node, li]) << (32 * li)
        for row, lo_i, hi_i in entries:
            if out[row]:
                continue
            kv_i = 0
            km_i = 0
            for li in range(LIMBS):
                kv_i |= int(kv[node, row, li]) << (32 * li)
                km_i |= int(km[node, row, li]) << (32 * li)
            hi_kb_i = kv_i | (~km_i & wm_int)
            if hi_kb_i < lo_i or kv_i > hi_i:
                out[row] = True

    must_false = (hi < 0.5) | (((km[..., 0] & 1) != 0) & ((kv[..., 0] & 1) == 0))
    for r in range(pack.n_rows):
        if out[r]:
            continue
        roots = pack.row_roots[r]
        if roots and must_false[roots, r].any():
            out[r] = True
    return out
