"""Pre-filter integration: feasibility pool publication and solver gates."""

import pytest

from mythril_tpu import absdomain
from mythril_tpu.observability import get_registry
from mythril_tpu.smt import terms


@pytest.fixture(autouse=True)
def _fresh():
    absdomain.reset_state()
    get_registry().reset(prefix="prefilter.")
    get_registry().reset(prefix="pipeline.")
    yield
    absdomain.reset_state()


def _unsat_raws(tag: str):
    x = terms.var(f"pfint_{tag}", 256)
    return [terms.eq(x, terms.const(1, 256)),
            terms.eq(x, terms.const(2, 256))]


# ---------------------------------------------------------------------------
# FeasibilityPool: verdict=False publication
# ---------------------------------------------------------------------------


def test_pool_prefilter_kill_skips_worker():
    from mythril_tpu.frontier.pipeline import FeasibilityPool

    pool = FeasibilityPool(workers=1)
    raws = _unsat_raws("kill")
    key = frozenset(t.tid for t in raws)
    pool.submit(0, "rec", 1, raws, key, verdict=False)
    # no worker ran: the verdict is already drainable
    assert [(s, ok) for s, _, _, ok, _ in pool.drain()] == [(0, False)]
    assert pool.pending() == 0
    reg = get_registry()
    assert reg.counter("pipeline.pool_prefilter_kills").value == 1
    assert not reg.counter("pipeline.pool_submitted").value
    pool.shutdown()


def test_pool_prefilter_kill_publishes_to_inflight_waiters():
    """Bugfix: a pre-filter kill must reach waiters ALREADY deduplicated
    under the same canonical key, not only the killed submission itself."""
    from mythril_tpu.frontier.pipeline import FeasibilityPool

    pool = FeasibilityPool(workers=1)
    raws = _unsat_raws("inflight")
    key = frozenset(t.tid for t in raws)
    # hold the solver lock so the exact worker cannot publish first
    with pool._solver_lock:
        pool.submit(0, "recA", 1, raws, key)            # exact, in flight
        pool.submit(1, "recB", 2, raws, key)            # dedup waiter
        pool.submit(2, "recC", 3, raws, key, verdict=False)  # abstract kill
        verdicts = sorted((s, ok) for s, _, _, ok, _ in pool.drain())
        # all three waiters already resolved, before the worker finished
        assert verdicts == [(0, False), (1, False), (2, False)]
    pool._executor.shutdown(wait=True)
    # the worker's late (key, ok) entry must not crash or re-publish
    assert pool.drain() == []
    assert pool.pending() == 0


def test_pool_duplicate_done_keys_tolerated():
    from mythril_tpu.frontier.pipeline import FeasibilityPool

    pool = FeasibilityPool(workers=1)
    raws = _unsat_raws("dup")
    key = frozenset(t.tid for t in raws)
    pool.submit(0, "recA", 1, raws, key, verdict=False)
    pool.submit(1, "recB", 1, raws, key, verdict=False)
    verdicts = sorted((s, ok) for s, _, _, ok, _ in pool.drain())
    assert verdicts == [(0, False), (1, False)]
    assert pool.drain() == []
    pool.shutdown()


# ---------------------------------------------------------------------------
# solver gates: tier 0.58 and the batched entry
# ---------------------------------------------------------------------------


def test_solve_conjunction_tier_058_kills(monkeypatch):
    from mythril_tpu.smt import solver
    from mythril_tpu.support.support_args import args as global_args

    monkeypatch.setattr(global_args, "prefilter", True, raising=False)
    solver.clear_model_cache()
    reg = get_registry()
    verdict, model = solver.solve_conjunction(_unsat_raws("t058"),
                                              use_cache=False)
    assert verdict == solver.UNSAT and model is None
    assert reg.counter("prefilter.killed").value == 1


def test_no_prefilter_flag_disables_gate(monkeypatch):
    from mythril_tpu.smt import solver
    from mythril_tpu.support.support_args import args as global_args

    monkeypatch.setattr(global_args, "prefilter", False, raising=False)
    solver.clear_model_cache()
    reg = get_registry()
    verdict, _ = solver.solve_conjunction(_unsat_raws("noflag"),
                                          use_cache=False)
    assert verdict == solver.UNSAT  # exact tiers still refute it
    assert not reg.counter("prefilter.evaluated").value


def test_batch_check_prefilter_gate(monkeypatch):
    from mythril_tpu.smt import solver
    from mythril_tpu.support.support_args import args as global_args

    monkeypatch.setattr(global_args, "prefilter", True, raising=False)
    solver.clear_model_cache()
    x = terms.var("pfint_batch_sat", 256)
    sat = [terms.ult(x, terms.const(10, 256))]
    rows = [sat, _unsat_raws("batch")]
    out = solver.check_satisfiable_batch(rows)
    assert out == [True, False]
    assert get_registry().counter("prefilter.killed").value >= 1
