"""ExternalCalls: call to a user-supplied address with enough gas for
reentrancy (SWC-107).

Reference parity: mythril/analysis/module/modules/external_calls.py:1-118.
"""

from __future__ import annotations

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import REENTRANCY
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core.transaction.symbolic import ACTORS
from mythril_tpu.smt import UGT, symbol_factory

DESCRIPTION = """
Search for external calls with unrestricted gas to a user-specified address.
"""


class ExternalCalls(DetectionModule):
    name = "External call to another contract"
    swc_id = REENTRANCY
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL"]
    # staticpass: external-call issues need a CALL
    static_required_ops = frozenset({"CALL"})

    def _execute(self, state: GlobalState) -> None:
        if self._cache_key(state) in self.cache:
            return None
        self._analyze_state(state)
        return None

    def _analyze_state(self, state: GlobalState) -> None:
        gas = state.mstate.stack[-1]
        to = state.mstate.stack[-2]
        if to.value is not None:
            return  # fixed target
        constraints = [
            to == ACTORS.attacker,
            UGT(gas, symbol_factory.BitVecVal(2300, 256)),
        ]
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.node.function_name if state.node else "unknown",
            address=state.get_current_instruction()["address"],
            swc_id=REENTRANCY,
            title="External Call To User-Supplied Address",
            severity="Low",
            bytecode=state.environment.code.bytecode,
            description_head="A call to a user-supplied address is executed.",
            description_tail=(
                "An external message call to an address specified by the caller "
                "is executed. Note that the callee account might contain "
                "arbitrary code and could re-enter any function within this "
                "contract. Reentering the contract in an intermediate state may "
                "lead to unexpected behaviour. Make sure that no state "
                "modifications are executed after this call and/or reentrancy "
                "guards are in place."
            ),
            detector=self,
            constraints=constraints,
        )
        get_potential_issues_annotation(state).potential_issues.append(potential_issue)


detector = ExternalCalls
