"""Shared fixtures for the service test package."""

import pytest


@pytest.fixture
def scoped_args():
    """The service arms the global flag object at start(); snapshot and
    restore it (plus the detector scope) so these tests do not leak
    configuration into the rest of the suite."""
    from mythril_tpu.facade.warm import reset_analysis_scope
    from mythril_tpu.support.support_args import args

    saved = dict(vars(args))
    yield
    vars(args).clear()
    vars(args).update(saved)
    # the service also re-armed the global query cache; point it back
    from mythril_tpu.querycache import configure as configure_query_cache

    configure_query_cache(
        enabled=getattr(args, "query_cache", True),
        cache_dir=getattr(args, "query_cache_dir", None),
    )
    reset_analysis_scope()
