"""MSTORE value gate: memory writes stop shipping events.

MSTORE left _ALWAYS_EVENT: carrier memory is rebuilt from the device word
table at terminals/parks (walker._restore_memory), and the only MSTORE
hook in the module set — UserAssertions' Panic(uint256) check — declares
``value_gated_hooks``, so the device events only symbolic stores and
concrete stores carrying the panic selector in their top 32 bits.
"""

from collections import namedtuple

import jax
import numpy as np
import pytest

from mythril_tpu.analysis.module.modules.user_assertions import PANIC_SELECTOR
from mythril_tpu.frontier import ops as O
from mythril_tpu.frontier.arena import HostArena
from mythril_tpu.frontier.code import CodeTables, stacked_device_tables
from mythril_tpu.frontier.state import Caps, empty_state
from mythril_tpu.frontier.step import ArenaDev, CfgScalars, CodeDev, cached_segment

Ins = namedtuple("Ins", "opcode address arg_int")

CAPS = Caps(B=2, K=16)


def _run_program(program, gated: bool, seed_ctx: bool = False) -> int:
    """Run ``program`` as one device segment; returns final ev_len."""
    arena = HostArena(CAPS.ARENA)
    row_zero = arena.const_row(0, 256)
    row_one = arena.const_row(1, 256)
    tables = CodeTables(
        program, arena,
        hooked_opcodes={"MSTORE"},
        value_gate_opcodes={"MSTORE"} if gated else None,
    )
    instr_cap, addr_cap, loops_cap = tables.size_bucket()
    segment = cached_segment(CAPS, 1, instr_cap, addr_cap, loops_cap)
    code_dev = CodeDev(*[
        jax.device_put(a)
        for a in stacked_device_tables([tables], (1, instr_cap, addr_cap, loops_cap))
    ])
    cfg = CfgScalars(
        max_depth=np.int32(128), loop_bound=np.int32(0),
        row_zero=np.int32(row_zero), row_one=np.int32(row_one),
        sel_mode=np.int32(0),
    )
    st = empty_state(CAPS, loops_cap)
    st.seed[0] = 0
    st.halt[0] = O.H_RUNNING
    if seed_ctx:
        from mythril_tpu.smt import terms as T

        st.ctx[0] = arena.var_row(T.var("seed_ctx", 256))
    dev_arena = ArenaDev(*[jax.device_put(a) for a in arena.device_arrays()])
    visited = jax.device_put(np.zeros((3, 1, instr_cap), bool))
    out_state, _a, _l, _n, _m, _v = segment(
        st, dev_arena, arena.length, visited, code_dev, cfg
    )
    return int(np.array(out_state.ev_len)[0])


def _run_mstore(value: int, gated: bool) -> int:
    """PUSH32 value; PUSH1 0; MSTORE; STOP — returns final ev_len."""
    return _run_program(
        [
            Ins("PUSH32", 0, value),
            Ins("PUSH1", 33, 0),
            Ins("MSTORE", 35, None),
            Ins("STOP", 36, None),
        ],
        gated=gated,
    )


def test_gated_nonpanic_store_ships_no_hook_event():
    # only the STOP terminal events
    assert _run_mstore(42, gated=True) == 1


def test_gated_symbolic_store_ships_no_hook_event():
    """The hook no-ops on symbolic values too (value.value is None), so a
    symbolic store — the common ABI-marshalling case — must not event."""
    program = [
        Ins("PUSH1", 0, 0),
        Ins("CALLDATALOAD", 2, None),
        Ins("PUSH1", 3, 0),
        Ins("MSTORE", 5, None),
        Ins("STOP", 6, None),
    ]
    assert _run_program(program, gated=True, seed_ctx=True) == 1


def test_gated_panic_store_still_events():
    panic_word = (PANIC_SELECTOR << 224) | 0x11  # Panic(0x11): overflow
    assert _run_mstore(panic_word, gated=True) == 2


def test_ungated_store_events_as_before():
    assert _run_mstore(42, gated=False) == 2


def test_mstore_not_always_evented_without_hooks():
    from mythril_tpu.frontier.code import _ALWAYS_EVENT

    assert "MSTORE" not in _ALWAYS_EVENT


def test_differential_panic_assertion_found():
    """A reachable solc panic store must be flagged identically host vs
    frontier (the gate must NOT suppress the panic event), and plain
    memory traffic before it must not break the exploit report (carrier
    memory restored from the word table)."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).parent))
    from test_frontier_engine import analyze, issue_keys

    # self-contained (Asm labels are absolute): scratch MSTOREs, then a
    # branch on calldata whose taken side writes a Panic(uint256) payload
    # to memory (the user_assertions pattern) and reverts
    from bench_contracts import Asm

    a = Asm()
    a.push(0x60).push(0x40).op("MSTORE")          # scratch write (gated)
    a.push(0).op("CALLDATALOAD")
    a.push(1).op("AND").jumpi("panic")
    a.op("STOP")
    a.label("panic")
    a.push(PANIC_SELECTOR << 224).push(0).op("MSTORE")
    a.push(0x11).push(4).op("MSTORE")
    a.push(0x24).push(0).op("REVERT")
    code = a.assemble().hex()

    host = analyze(code, modules=["UserAssertions"])
    dev = analyze(code, modules=["UserAssertions"], frontier=True)
    assert issue_keys(host) == issue_keys(dev)
    assert any(i.swc_id == "110" for i in host), "panic assertion not found"
