"""SymExecWrapper: end-to-end orchestration of one contract's analysis.

Reference parity: mythril/analysis/symbolic.py:39-312 — strategy selection,
engine construction, bounded-loops wrapping, default plugin loading, detection
module hook registration, CREATOR/ATTACKER world-state seeding, creation vs
runtime execution, and post-hoc Call-op extraction from the statespace.
"""

from __future__ import annotations

import copy
import logging
from typing import List, Optional, Union

from mythril_tpu.analysis.module.base import EntryPoint
from mythril_tpu.analysis.module.loader import ModuleLoader
from mythril_tpu.analysis.module.util import get_detection_module_hooks
from mythril_tpu.analysis.ops import Call, Variable, VarType, get_variable
from mythril_tpu.core.state.world_state import WorldState
from mythril_tpu.core.strategy.basic import (
    BasicSearchStrategy,
    BeamSearch,
    BreadthFirstSearchStrategy,
    DepthFirstSearchStrategy,
    ReturnRandomNaivelyStrategy,
    ReturnWeightedRandomStrategy,
)
from mythril_tpu.core.strategy.extensions.bounded_loops import BoundedLoopsStrategy
from mythril_tpu.core.svm import LaserEVM
from mythril_tpu.core.transaction.symbolic import ACTORS
from mythril_tpu.observability import tracer as _otrace
from mythril_tpu.plugins.loader import LaserPluginLoader
from mythril_tpu.plugins.plugins.call_depth_limiter import CallDepthLimitBuilder
from mythril_tpu.plugins.plugins.coverage import CoveragePluginBuilder
from mythril_tpu.plugins.plugins.dependency_pruner import DependencyPrunerBuilder
from mythril_tpu.plugins.plugins.instruction_profiler import InstructionProfilerBuilder
from mythril_tpu.plugins.plugins.mutation_pruner import MutationPrunerBuilder
from mythril_tpu.support.support_args import args

log = logging.getLogger(__name__)


class SymExecWrapper:
    def __init__(
        self,
        contract,
        address,
        strategy: str = "dfs",
        dynloader=None,
        max_depth: int = 128,
        execution_timeout: Optional[int] = None,
        loop_bound: int = 3,
        create_timeout: Optional[int] = None,
        transaction_count: int = 2,
        modules: Optional[List[str]] = None,
        compulsory_statespace: bool = True,
        disable_dependency_pruning: bool = False,
        run_analysis_modules: bool = True,
        enable_coverage_strategy: bool = False,
        custom_modules_directory: str = "",
        checkpoint_path: Optional[str] = None,
        resume_from: Optional[str] = None,
        defer_exec: bool = False,
    ):
        if isinstance(address, str):
            address = int(address, 16)
        self.address = address

        strategy_cls = {
            "dfs": DepthFirstSearchStrategy,
            "bfs": BreadthFirstSearchStrategy,
            "naive-random": ReturnRandomNaivelyStrategy,
            "weighted-random": ReturnWeightedRandomStrategy,
            "beam-search": BeamSearch,
            "pending": DepthFirstSearchStrategy,
        }.get(strategy)
        if strategy_cls is None:
            raise ValueError(f"invalid search strategy: {strategy}")

        requires_statespace = compulsory_statespace or run_analysis_modules

        # forced device backend: compile the probe interpreter BEFORE engine
        # timers start (the one-time XLA compile must not eat the creation-tx
        # timeout); best-effort — failure degrades to the host path.  The
        # "auto" backend instead warms lazily in the background when a query
        # first crosses the device break-even (solver._try_compile_device)
        # and uses the host path until ready.
        if args.probe_backend == "jax":
            try:
                from mythril_tpu.ops import tape_vm

                tape_vm.warmup()
            except Exception as e:
                log.warning("device probe warmup failed (host fallback): %s", e)

        # seed world state with the actor accounts (reference symbolic.py:100-117)
        world_state = WorldState()
        world_state.accounts_exist_or_load(ACTORS.creator.value, dynloader)
        attacker_acct = world_state.accounts_exist_or_load(ACTORS.attacker.value, dynloader)

        self.laser = LaserEVM(
            dynamic_loader=dynloader,
            max_depth=max_depth,
            execution_timeout=execution_timeout,
            create_timeout=create_timeout,
            strategy=strategy_cls,
            transaction_count=transaction_count,
            requires_statespace=requires_statespace,
        )

        self.laser.checkpoint_path = checkpoint_path or args.checkpoint_path
        self._resume_from = resume_from or args.resume_from

        if loop_bound is not None:
            self.laser.extend_strategy(BoundedLoopsStrategy, loop_bound=loop_bound)

        plugin_loader = LaserPluginLoader()
        plugin_loader.load(CoveragePluginBuilder())
        plugin_loader.load(MutationPrunerBuilder())
        plugin_loader.load(CallDepthLimitBuilder())
        if args.enable_iprof:
            plugin_loader.load(InstructionProfilerBuilder())
        self._benchmark_plugin = None
        if args.benchmark_path:
            # instantiated directly (not via the loader) so the series can be
            # written out after execution (reference benchmark.py:19-94)
            from mythril_tpu.plugins.plugins.benchmark import BenchmarkPlugin

            self._benchmark_plugin = BenchmarkPlugin()
            self._benchmark_plugin.initialize(self.laser)
        plugin_loader.add_args("call-depth-limit", call_depth_limit=args.call_depth_limit)
        if not disable_dependency_pruning:
            plugin_loader.load(DependencyPrunerBuilder())
        plugin_loader.instrument_virtual_machine(self.laser)

        if enable_coverage_strategy:
            from mythril_tpu.plugins.plugins.coverage import (
                CoverageStrategy,
                InstructionCoverage,
            )

            coverage_plugin = InstructionCoverage()
            coverage_plugin.initialize(self.laser)
            self.laser.strategy = CoverageStrategy(self.laser.strategy, coverage_plugin)

        if custom_modules_directory:
            ModuleLoader().load_custom_modules(custom_modules_directory)

        if run_analysis_modules:
            # static pre-analysis gate: modules statically proven
            # irrelevant for this contract never register their hooks
            # (mythril_tpu/staticpass — over-approximate, so the issue
            # set is unchanged; --no-staticpass restores blind wiring)
            from mythril_tpu.staticpass import gate_view_for_contract

            static_view = gate_view_for_contract(
                contract, dynloader=dynloader, resume_from=self._resume_from
            )
            analysis_modules = ModuleLoader().get_detection_modules(
                EntryPoint.CALLBACK, white_list=modules, static_view=static_view
            )
            self.laser.register_hooks(
                hook_type="pre",
                hook_dict=get_detection_module_hooks(analysis_modules, "pre"),
            )
            self.laser.register_hooks(
                hook_type="post",
                hook_dict=get_detection_module_hooks(analysis_modules, "post"),
            )

        # deferred execution: the cooperative corpus driver owns the tx loop
        # (analysis/cooperative.py) — set up the account, stash the world
        # state, and skip both execution and statespace post-processing
        self.deferred_world_state: Optional[WorldState] = None
        if defer_exec:
            if not isinstance(contract, (bytes, bytearray)):
                raise ValueError("defer_exec supports raw runtime bytecode only")
            from mythril_tpu.frontend.disassembler import Disassembly

            acct = world_state.create_account(
                balance=0, address=address, concrete_storage=False
            )
            acct.code = Disassembly(bytes(contract))
            self.deferred_world_state = world_state
            return

        # execute (creation vs runtime, reference symbolic.py:168-220)
        with _otrace.span("analysis.sym_exec", cat="analysis"):
            if self._resume_from:
                self._exec_resumed(address)
            elif isinstance(contract, (bytes, bytearray)):
                # raw runtime bytecode
                from mythril_tpu.frontend.disassembler import Disassembly

                acct = world_state.create_account(
                    balance=0, address=address, concrete_storage=False
                )
                acct.code = Disassembly(bytes(contract))
                self.laser.sym_exec(
                    world_state=world_state, target_address=address
                )
            elif getattr(contract, "creation_code", None):
                self._exec_creation(contract, world_state)
            else:
                acct = world_state.create_account(
                    balance=0, address=address, concrete_storage=False
                )
                acct.code = contract.disassembly
                acct.contract_name = getattr(contract, "name", "Unknown")
                self.laser.sym_exec(
                    world_state=world_state, target_address=address
                )

        if self._benchmark_plugin is not None:
            try:
                self._benchmark_plugin.write_to_file(args.benchmark_path)
            except OSError as e:
                log.warning("could not write benchmark series: %s", e)

        if not requires_statespace:
            return

        self.nodes = self.laser.nodes
        self.edges = self.laser.edges
        self._parse_calls()

    def finalize(self) -> None:
        """Deferred-run epilogue: benchmark series + statespace post-
        processing, exactly what the eager constructor path does after
        execution.  Called by the cooperative driver once its tx loop ends."""
        if self._benchmark_plugin is not None:
            try:
                self._benchmark_plugin.write_to_file(args.benchmark_path)
            except OSError as e:
                log.warning("could not write benchmark series: %s", e)
        if not self.laser.requires_statespace:
            return
        self.nodes = self.laser.nodes
        self.edges = self.laser.edges
        self._parse_calls()

    def _exec_resumed(self, address: int) -> None:
        """Continue a checkpointed run: reload the frontier and hand the
        engine the restored open states (LaserEVM.resume owns the framing)."""
        from mythril_tpu.support.checkpoint import load_checkpoint

        completed, open_states, saved_address = load_checkpoint(
            self._resume_from, dynamic_loader=self.laser.dynamic_loader
        )
        if saved_address is not None:
            address = saved_address
        log.info(
            "resuming from %s: %d transactions done, %d open states",
            self._resume_from,
            completed,
            len(open_states),
        )
        self.laser.resume(open_states, completed, address)

    def _exec_creation(self, contract, world_state: WorldState) -> None:
        from mythril_tpu.core.transaction import symbolic as sym_tx

        self.laser._fire("start_sym_exec")
        from mythril_tpu.support.time_handler import time_handler

        time_handler.start_execution(self.laser.execution_timeout)
        created = sym_tx.execute_contract_creation(
            self.laser,
            contract.creation_code,
            getattr(contract, "name", "MAIN"),
            world_state=world_state,
        )
        if created is not None and created.address.value is not None:
            self.laser._execute_transactions(created.address.value)
        self.laser._fire("stop_sym_exec")

    # -- statespace post-processing (reference symbolic.py:228-308) ---------

    def _parse_calls(self) -> None:
        self.calls: List[Call] = []
        for key in self.nodes:
            for state in self.nodes[key].states:
                instruction = state.get_current_instruction()
                op = instruction["opcode"]
                if op in ("CALL", "CALLCODE", "DELEGATECALL", "STATICCALL"):
                    stack = state.mstate.stack
                    required = 7 if op in ("CALL", "CALLCODE") else 6
                    if len(stack) < required:
                        continue
                    if op in ("CALL", "CALLCODE"):
                        gas, to, value = (
                            get_variable(stack[-1]),
                            get_variable(stack[-2]),
                            get_variable(stack[-3]),
                        )
                        self.calls.append(
                            Call(self.nodes[key], state, None, op, to, gas, value)
                        )
                    else:
                        gas, to = get_variable(stack[-1]), get_variable(stack[-2])
                        self.calls.append(
                            Call(self.nodes[key], state, None, op, to, gas)
                        )
