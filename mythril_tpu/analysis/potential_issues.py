"""Deferred issues: modules park constraints, the engine solves once per tx end.

Reference parity: mythril/analysis/potential_issues.py:82-126 — modules create
PotentialIssue records (no model yet) on a state annotation;
check_potential_issues solves each at transaction end, converting the solvable
ones into confirmed Issues with concrete transaction sequences.  The
annotation's search_importance (10 x #issues) steers beam search (:61-62).
"""

from __future__ import annotations

import logging
from functools import lru_cache
from typing import List

from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.core.state.annotation import StateAnnotation
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.exceptions import UnsatError

log = logging.getLogger(__name__)


class PotentialIssue:
    def __init__(
        self,
        contract: str,
        function_name: str,
        address: int,
        swc_id: str,
        title: str,
        bytecode,
        detector,
        severity: str = "Medium",
        description_head: str = "",
        description_tail: str = "",
        constraints=None,
    ):
        self.contract = contract
        self.function_name = function_name
        self.address = address
        self.swc_id = swc_id
        self.title = title
        self.bytecode = bytecode
        self.severity = severity
        self.description_head = description_head
        self.description_tail = description_tail
        self.detector = detector
        self.constraints = constraints or []


class PotentialIssuesAnnotation(StateAnnotation):
    def __init__(self):
        self.potential_issues: List[PotentialIssue] = []

    @property
    def search_importance(self) -> int:
        return 10 * len(self.potential_issues)

    def __copy__(self):
        # shared across forks on purpose: issues park once per program point
        return self


def get_potential_issues_annotation(global_state: GlobalState) -> PotentialIssuesAnnotation:
    for annotation in global_state.get_annotations(PotentialIssuesAnnotation):
        return annotation
    annotation = PotentialIssuesAnnotation()
    global_state.annotate(annotation)
    return annotation


def check_potential_issues(global_state: GlobalState) -> None:
    """Called by the engine at outermost transaction end (svm counterpart of
    reference svm.py:423).

    The sat/unsat GATE over all parked issues runs as ONE batched sweep
    first (the sets share the whole path prefix — union model replay and
    merged dispatch resolve most), so the per-issue exploit synthesis
    (model + input minimization) is paid only for the satisfiable ones."""
    annotation = get_potential_issues_annotation(global_state)
    # the detector's (address, bytecode-hash) cache is the reference's
    # dedup discipline (module/base.py:70-95, checked at analyze time);
    # multiple paths park the same program point before the first
    # confirmation lands, so re-check here — each duplicate skipped is a
    # full exploit-synthesis solve saved
    pending: List[PotentialIssue] = []
    for p in annotation.potential_issues:
        key = (p.address, get_bytecode_hash(p.bytecode))
        if key in p.detector.cache:
            continue
        pending.append(p)
    unsolved: List[PotentialIssue] = []
    gate = _gate_issues(global_state, pending)
    for potential_issue, feasible in zip(pending, gate):
        if not feasible:
            # an UNKNOWN here degrades exactly like a failed solve below:
            # the issue stays parked and is retried at a later tx end
            unsolved.append(potential_issue)
            continue
        key = (
            potential_issue.address,
            get_bytecode_hash(potential_issue.bytecode),
        )
        if key in potential_issue.detector.cache:
            continue  # confirmed earlier in this same sweep
        try:
            transaction_sequence = get_transaction_sequence(
                global_state,
                global_state.world_state.constraints + potential_issue.constraints,
            )
        except UnsatError:
            unsolved.append(potential_issue)
            continue
        potential_issue.detector.cache.add(
            (potential_issue.address, get_bytecode_hash(potential_issue.bytecode))
        )
        potential_issue.detector.issues.append(
            Issue(
                contract=potential_issue.contract,
                function_name=potential_issue.function_name,
                address=potential_issue.address,
                title=potential_issue.title,
                bytecode=potential_issue.bytecode,
                swc_id=potential_issue.swc_id,
                gas_used=(
                    global_state.mstate.min_gas_used,
                    global_state.mstate.max_gas_used,
                ),
                description_head=potential_issue.description_head,
                description_tail=potential_issue.description_tail,
                severity=potential_issue.severity,
                transaction_sequence=transaction_sequence,
            )
        )
    annotation.potential_issues = unsolved


@lru_cache(maxsize=512)
def _code_hash_memo(bytecode) -> str:
    from mythril_tpu.support.support_utils import get_code_hash

    return get_code_hash(bytecode)


def get_bytecode_hash(bytecode) -> str:
    # every tx-end sweep keys each parked issue by this hash; keccak over
    # the full runtime bytecode is far too expensive to recompute per issue
    if bytecode is None:
        return ""
    return _code_hash_memo(
        bytecode if isinstance(bytecode, (str, bytes)) else str(bytecode)
    )


def _gate_issues(global_state: GlobalState, issues: List[PotentialIssue]):
    """sat/unsat gate over all parked issues at FULL solver budget.

    All issues at one transaction end share the whole path prefix, so the
    gate blasts ``path ∪ all issue constraints`` ONCE into an incremental
    CDCL session with per-issue enable literals and answers each issue as a
    solve-under-assumptions (learned clauses shared).  Exact UNSATs skip
    the expensive exploit synthesis; SAT models are validated exactly;
    anything undecidable here (UNKNOWN, unsupported structure, wide-mul
    overflow encodings, no native library) passes through True to the full
    per-issue solve — the gate can only SAVE work, never lose recall beyond
    what the full solve itself would."""
    gate = [True] * len(issues)
    if len(issues) < 2:
        return gate
    from mythril_tpu.native import bitblast
    from mythril_tpu.smt.concrete_eval import evaluate
    from mythril_tpu.smt.solver import SolverStatistics
    from mythril_tpu.support.support_args import args
    from mythril_tpu.support.time_handler import time_handler

    if not bitblast.available():
        return gate
    path_raws = list(global_state.world_state.constraints.get_all_raw())
    issue_raws = [
        [c.raw if hasattr(c, "raw") else c for c in p.constraints]
        for p in issues
    ]
    # one enable-guarded conjunct per issue (land folds multi-term lists)
    from mythril_tpu.smt import terms as T

    # wide-mul overflow encodings included: the session blasts select
    # congruence lazily (bb_extend refinement), so the Dadda 512-bit
    # multiply no longer exceeds the clause budget — SWC-101 confirmations,
    # the most expensive class, now share the gate like everything else.
    # Should the full blast STILL overflow a budget, retry without the
    # wide-mul members rather than losing the gate for every issue.
    def _wide_mul(t) -> bool:
        return any(
            x.op == "bvmul" and T.is_bv_sort(x.sort) and x.width > 256
            for x in T.topo_order([t])
        )

    folded_all = [
        T.land(*raws) if raws else T.boolval(True) for raws in issue_raws
    ]
    attempts = [list(range(len(folded_all)))]
    narrow = [i for i in attempts[0] if not _wide_mul(folded_all[i])]
    if len(narrow) < len(folded_all):
        attempts.append(narrow)
    session = None
    members: List[int] = []
    for candidate_members in attempts:
        if len(candidate_members) < 2:
            return gate
        try:
            session = bitblast.OptimizeSession(
                path_raws, guarded=[folded_all[i] for i in candidate_members]
            )
            members = candidate_members
            break
        except bitblast.Unsupported:
            continue
    if session is None:
        return gate
    guarded = [folded_all[i] for i in members]
    try:
        for gi, i in enumerate(members):
            # the OVERALL analysis deadline is re-read per query: one hard
            # issue must not spend the whole remaining budget N times over
            budget_s = max(0.05, min(
                args.solver_timeout / 1000.0,
                max(time_handler.time_remaining(), 0) / 2,
            ))
            SolverStatistics().cdcl_calls += 1
            status, asg = session.solve([], budget_s, enable=[gi])
            if status == bitblast.UNSAT:
                gate[i] = False
            elif status == bitblast.SAT and asg is not None:
                # exact validation, as for every native SAT model; a valid
                # model is remembered so the full solve's replay tier hits
                conj = path_raws + [guarded[gi]]
                try:
                    vals = evaluate(conj, asg)
                    if all(vals[c] for c in conj):
                        from mythril_tpu.smt.solver import remember_model

                        remember_model(conj, asg)
                except Exception:
                    pass  # full solve decides from scratch
    finally:
        session.close()
    return gate
