"""Mesh-sharded probe evaluation: the frontier step and flat batched eval.

``frontier_step(compiled)`` is the flagship SPMD program: one round of the
probe solver over a stacked frontier of P independent paths x B candidate
assignments each.  Inputs are sharded [path, cand] over the 2-D mesh; the
step evaluates every conjunct for every candidate, reduces to per-path best
scores (collectives across ``cand``) and a global sat count (collectives
across both axes) — XLA places the all-reduces on ICI.

The reference's counterpart is strictly sequential: one Z3 ``check()`` per
path per prune (mythril/laser/ethereum/svm.py:287-292,
mythril/laser/smt/solver/solver.py:51-66).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from mythril_tpu.ops.lowering import CompiledConjunction, pack_assignments
from mythril_tpu.parallel.mesh import make_frontier_mesh, shard_probe_args


def frontier_step(compiled: CompiledConjunction):
    """Build the jittable one-round frontier program for a conjunction shape.

    Returns ``step(scalars, bools, array_tabs, valid)`` expecting leading
    [P, B] batch dims on every args leaf and the [P, B] ``valid`` mask from
    ``pack_frontier``, producing:
      * ``scores``      [P, B] — satisfied-conjunct count per candidate,
                                 ``-1`` in masked (padding) slots,
      * ``best_score``  [P]    — per-path max (cross-``cand`` reduction),
      * ``best_idx``    [P]    — argmax candidate per path,
      * ``n_sat``       []     — global count of full models (cross-mesh),
                                 padding excluded.
    """
    n_conj = len(compiled.conjuncts)
    raw = compiled.raw_fn

    def step(scalars, bools, array_tabs, valid):
        truth = raw(scalars, bools, array_tabs)  # [P, B, C] bool
        scores = truth.sum(axis=-1)  # [P, B]
        # Padding rows (ragged frontier made rectangular) must never win
        # the argmax nor count as models.
        scores = jnp.where(valid, scores, -1)
        best_score = scores.max(axis=-1)  # [P]
        best_idx = jnp.argmax(scores, axis=-1)  # [P]
        n_sat = (scores == n_conj).sum()  # []
        return scores, best_score, best_idx, n_sat

    return jax.jit(step)


def pack_frontier(
    compiled: CompiledConjunction, assignments_per_path: Sequence[Sequence]
):
    """Pack P lists of assignments into stacked [P, B, ...] probe inputs.

    All paths share the conjunction DAG (SPMD requires one program); array
    tables take the union of keys across the whole frontier so every leaf is
    rectangular.  Paths may carry different candidate counts: short paths are
    padded to the longest by repeating their last candidate, and the returned
    ``valid`` [P, B] mask marks the real rows.  Feed ``valid`` to the
    ``frontier_step`` program so padding can't double-count in ``n_sat`` or
    win ``best_idx``.

    Returns ``(args_tree, valid)``.
    """
    P_ = len(assignments_per_path)
    counts = [len(a) for a in assignments_per_path]
    if not counts or not all(counts):
        raise ValueError("every path needs at least one candidate")
    B = max(counts)
    flat: List = []
    for path in assignments_per_path:
        flat.extend(path)
        flat.extend([path[-1]] * (B - len(path)))
    scalars, bools, array_tabs = pack_assignments(compiled, flat)

    def unflatten(leaf):
        return leaf.reshape((P_, B) + leaf.shape[1:])

    valid = np.zeros((P_, B), dtype=bool)
    for p, c in enumerate(counts):
        valid[p, :c] = True
    return jax.tree.map(unflatten, (scalars, bools, array_tabs)), valid


def _pad_batch(args_tree, pad_to: int, batch: int):
    """Pad the leading candidate dim by repeating the last row.

    Returns ``(args_tree, valid)`` where ``valid`` [pad_to] marks real rows —
    consumers reducing over the batch (n_sat, argmax) must apply it; slicing
    ``[:batch]`` off a gathered result is the equivalent for element-wise use.
    """
    valid = np.arange(pad_to) < batch
    if pad_to == batch:
        return args_tree, valid

    def pad(leaf):
        reps = np.concatenate(
            [leaf[:batch], np.repeat(np.asarray(leaf[batch - 1 : batch]), pad_to - batch, axis=0)]
        )
        return reps

    return jax.tree.map(lambda leaf: pad(np.asarray(leaf)), args_tree), valid


def evaluate_batch_sharded(
    compiled: CompiledConjunction,
    assignments: Sequence,
    mesh: Optional[Mesh] = None,
) -> np.ndarray:
    """[B, C] truth matrix with the candidate batch sharded over all devices.

    The flat data-parallel production path used by the solver when more than
    one device is attached: candidates spread over the whole mesh (both axes
    flattened), one XLA dispatch, result gathered to host.  Padding rows
    (batch made divisible by the device count) are sliced off before return.
    """
    mesh = mesh or make_frontier_mesh()
    n_dev = mesh.devices.size
    B = len(assignments)
    pad_to = -(-B // n_dev) * n_dev
    args_tree = pack_assignments(compiled, assignments)
    args_tree, _valid = _pad_batch(args_tree, pad_to, B)
    scalars, bools, array_tabs = shard_probe_args(args_tree, mesh, batch_dims=1)
    truth = compiled._fn(scalars, bools, array_tabs)
    return np.asarray(truth)[:B]
