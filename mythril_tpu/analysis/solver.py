"""Solver glue: turn a satisfiable state into a concrete transaction sequence.

Reference parity: mythril/analysis/solver.py:51-256 — get_transaction_sequence
solves the path constraints with calldata-size/callvalue minimization and
balance sanity bounds, reifies concrete initial state and per-tx calldata, and
post-processes symbolic hash placeholders (here unnecessary: keccak terms are
concrete under any model by construction).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from mythril_tpu.core.state.constraints import Constraints
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.core.transaction.transaction_models import (
    BaseTransaction,
    ContractCreationTransaction,
)
from mythril_tpu.exceptions import UnsatError
from mythril_tpu.observability import tracer as _otrace
from mythril_tpu.smt import UGE, ULE, symbol_factory
from mythril_tpu.smt.solver import Model
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)


def get_transaction_sequence(
    global_state: GlobalState,
    constraints: Constraints,
    session=None,
    session_enable=(),
) -> Dict:
    """Generate concrete transaction sequence satisfying ``constraints``.

    Raises UnsatError if no model exists/was found.

    ``session``/``session_enable``: the tx-end issue gate's live CDCL
    session (analysis/potential_issues.py), blasted once over the shared
    path prefix + sanity bounds + these same minimization objectives, with
    this issue's constraints behind the enable literal — the confirmation
    solve then answers everything under assumptions instead of re-blasting
    (the reference pays exactly one z3.Optimize per issue,
    mythril/analysis/solver.py:51-101; this matches that solve count).
    """
    transaction_sequence = global_state.world_state.transaction_sequence
    concrete_transactions = []

    tx_constraints, minimize = _set_minimisation_constraints(
        transaction_sequence, constraints.copy(), [], 5000, global_state.world_state
    )
    # issue confirmation is one of the query cache's three entry points
    # (ISSUE/querycache.rst): the solve below flows through the solver's
    # cache hook; the span records how much of it the cache absorbed
    from mythril_tpu.querycache import get_query_cache

    qc_hits_before = get_query_cache().hits_total()
    with _otrace.span("analysis.confirm_solve", cat="analysis") as sp:
        model = get_model(
            tx_constraints,
            minimize=minimize,
            session=session,
            session_enable=session_enable,
        )
        sp.set(querycache_hits=get_query_cache().hits_total() - qc_hits_before)

    # keccak terms evaluate concretely under the model — no sha replacement
    # pass needed (reference needed _replace_with_actual_sha, solver.py:128-164)
    min_price_dict: Dict[str, int] = {}
    for transaction in transaction_sequence:
        concrete_transaction = _get_concrete_transaction(model, transaction)
        concrete_transactions.append(concrete_transaction)
        caller = concrete_transaction["origin"]
        value = int(concrete_transaction["value"], 16)
        min_price_dict[caller] = min_price_dict.get(caller, 0) + value

    if isinstance(transaction_sequence[0], ContractCreationTransaction):
        initial_accounts = transaction_sequence[0].prev_world_state.accounts
    else:
        initial_accounts = transaction_sequence[0].world_state.accounts

    concrete_initial_state = _get_concrete_state(initial_accounts, min_price_dict)
    steps = {"initialState": concrete_initial_state, "steps": concrete_transactions}
    return steps


def _get_concrete_state(initial_accounts: Dict, min_price_dict: Dict[str, int]) -> Dict:
    """Concrete initial account states (reference solver.py:166-182)."""
    accounts = {}
    for address, account in initial_accounts.items():
        address_str = f"0x{address:040x}" if isinstance(address, int) else str(address)
        data: Dict = {"nonce": account.nonce, "code": account.serialised_code, "storage": {}}
        data["balance"] = hex(min_price_dict.get(address_str, 0))
        accounts[address_str] = data
    return {"accounts": accounts}


def _get_concrete_transaction(model: Model, transaction: BaseTransaction) -> Dict:
    """Reify one transaction's concrete inputs (reference solver.py:184-213)."""
    caller = f"0x{int(model.eval(transaction.caller)):040x}"
    value = hex(int(model.eval(transaction.call_value)))
    if isinstance(transaction, ContractCreationTransaction):
        address = ""
        # deployment input = creation bytecode || ABI-encoded constructor
        # arguments: the constructor reads them from the tail of the init
        # input, so a creation step without the model's calldata suffix is
        # not replayable (reference solver.py:195-198 emits both; calldata
        # size is minimized, so argument-less constructors append nothing)
        input_ = (
            transaction.code.bytecode.hex()
            + bytes(transaction.call_data.concrete(model)).hex()
        )
    else:
        address = f"0x{int(model.eval(transaction.callee_account.address)):040x}"
        input_ = bytes(transaction.call_data.concrete(model)).hex()
    return {
        "address": address,
        "calldata": "0x" + input_,
        "input": "0x" + input_,
        "name": "unknown",
        "origin": caller,
        "value": value,
    }


def _set_minimisation_constraints(
    transaction_sequence, constraints: Constraints, minimize: List, max_size: int, world_state
):
    """Add sanity bounds + minimization targets (reference solver.py:216-256)."""
    for transaction in transaction_sequence:
        # reasonable calldata size bound
        constraints.append(
            ULE(transaction.call_data.calldatasize, symbol_factory.BitVecVal(max_size, 256))
        )
        # no caller pays more than ~10 ETH (keeps models human-readable)
        constraints.append(
            ULE(transaction.call_value, symbol_factory.BitVecVal(10**19, 256))
        )
        minimize.append(transaction.call_data.calldatasize)
        minimize.append(transaction.call_value)
    return constraints, tuple(minimize)
