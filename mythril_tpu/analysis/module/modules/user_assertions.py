"""UserAssertions: user-defined assertion messages / solc panics (SWC-110).

Reference parity: mythril/analysis/module/modules/user_assertions.py:1-129 —
decodes the MythX `AssertionFailed(string)` log event and the solc
``Panic(uint256)`` / ``Error(string)`` revert payloads.
"""

from __future__ import annotations

from typing import List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import ASSERT_VIOLATION
from mythril_tpu.core.state.global_state import GlobalState
from mythril_tpu.exceptions import UnsatError

DESCRIPTION = "Search for reachable user-supplied exceptions (hidden assertions)."

# keccak("AssertionFailed(string)")[:32]
ASSERTION_FAILED_TOPIC = 0xB42604CB105A16C8F6DB8A41E6B00C0C1B4826465E8BC504B3EB3E88B3E6A4A0

# solc >=0.8 Panic(uint256) selector
from mythril_tpu.analysis.swc_data import PANIC_SELECTOR


class UserAssertions(DetectionModule):
    name = "A user-defined assertion has been triggered"
    swc_id = ASSERT_VIOLATION
    description = DESCRIPTION
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["LOG1", "MSTORE"]
    # staticpass: panic-MSTORE / assertion-LOG1 are the only triggers
    static_required_ops = frozenset({"LOG1", "MSTORE"})
    # the MSTORE hook observes ONLY concrete values whose top 32 bits are
    # the Panic(uint256) selector (line 51; symbolic values no-op too):
    # the device may skip the event for every other store
    # (frontier/code.py value gate)
    value_gated_hooks = frozenset({"MSTORE"})

    def _execute(self, state: GlobalState) -> Optional[List[Issue]]:
        if self._cache_key(state) in self.cache:
            return None
        return self._analyze_state(state)

    def _analyze_state(self, state: GlobalState) -> List[Issue]:
        opcode = state.get_current_instruction()["opcode"]
        message = None
        if opcode == "LOG1":
            # stack: ... offset length topic
            topic = state.mstate.stack[-3]
            if topic.value != ASSERTION_FAILED_TOPIC:
                return []
            message = "user-provided assertion"
        else:  # MSTORE of a Panic(uint256) payload
            value = state.mstate.stack[-2]
            if value.value is None or (value.value >> (256 - 32)) != PANIC_SELECTOR:
                return []
            message = "solidity panic"

        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints.get_all_constraints()
            )
        except UnsatError:
            return []
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.node.function_name if state.node else "unknown",
                address=state.get_current_instruction()["address"],
                swc_id=ASSERT_VIOLATION,
                title="Exception State",
                severity="Medium",
                bytecode=state.environment.code.bytecode,
                description_head=f"A reachable exception has been detected ({message}).",
                description_tail=(
                    "It is possible to trigger an exception. Exceptions in "
                    "Solidity indicate that an invariant has been violated; make "
                    "sure this condition is not reachable with valid user input."
                ),
                gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
                transaction_sequence=transaction_sequence,
            )
        ]


detector = UserAssertions
