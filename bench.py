"""End-to-end benchmark: killbilly-style multi-transaction exploit search.

Workload (mirrors the reference's README headline demo, `myth a killbilly.sol
-t 3`, and BASELINE.md config #2): a contract whose SELFDESTRUCT is gated on
a storage flag set by a prior transaction, so the analyzer must chain two
symbolic transactions (activate() then kill()) and synthesize concrete
calldata for both.  Recall is asserted — the run only counts if the
Unprotected-Selfdestruct issue (SWC-106) is actually found with a valid
2-step transaction sequence.

Metric: explored states per second in the PRODUCTION configuration
(`probe_backend="auto"`: the latency-aware hybrid that dispatches a query to
the TPU tape-VM probe only past the host/device break-even, keeps the host
big-int evaluator for cheap queries, and backs both with the native CDCL
tier); ``vs_baseline`` is the speedup over the identical run forced to the
host-only probe (`probe_backend="host"`), the stand-in for the reference's
CPU solver path — the mounted reference itself cannot run here (no z3 wheel
in the image; see BASELINE.md).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

# activate() selector 0x0a11ce00 -> 0x1e, kill() selector 0x41c0e1b5 -> 0x25
DISPATCH = (
    "6000"  # PUSH1 0
    "35"  # CALLDATALOAD
    "60e0"  # PUSH1 0xe0
    "1c"  # SHR
    "80"  # DUP1
    "630a11ce00"  # PUSH4 activate()
    "14"  # EQ
    "601e"  # PUSH1 0x1e
    "57"  # JUMPI
    "6341c0e1b5"  # PUSH4 kill()
    "14"  # EQ
    "6025"  # PUSH1 0x25
    "57"  # JUMPI
    "60006000fd"  # REVERT(0, 0)
)
ACTIVATE = "5b600160005500"  # 0x1e: JUMPDEST; SSTORE(0, 1); STOP
KILL = (  # 0x25: JUMPDEST; require(storage[0] == 1); SELFDESTRUCT(CALLER)
    "5b" "600054" "6001" "14" "6034" "57" "60006000fd" "5b" "33" "ff"
)
KILLBILLY = DISPATCH + ACTIVATE + KILL
# constructor: CODECOPY the runtime code to memory and RETURN it
_L = f"{len(KILLBILLY) // 2:02x}"
KILLBILLY_CREATION = f"60{_L}600c60003960{_L}6000f3" + KILLBILLY


def run_analysis(probe_backend: str):
    from mythril_tpu.analysis.security import fire_lasers, reset_callback_modules
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.frontend.evmcontract import EVMContract
    from mythril_tpu.support.support_args import args as global_args

    global_args.probe_backend = probe_backend
    reset_callback_modules()
    # both configurations must solve from scratch: drop memoized models at
    # both cache tiers (solver-level model reuse AND get_model's lru_cache)
    from mythril_tpu.smt.solver import clear_model_cache
    from mythril_tpu.support.model import _get_model_cached

    clear_model_cache()
    _get_model_cached.cache_clear()
    # the (address, bytecode-hash) issue dedup cache persists across runs in
    # one process; both configurations must analyze from scratch
    from mythril_tpu.analysis.module.loader import ModuleLoader

    for module in ModuleLoader().get_detection_modules():
        module.cache.clear()
    contract = EVMContract(
        code=KILLBILLY, creation_code=KILLBILLY_CREATION, name="KillBilly"
    )
    t0 = time.time()
    sym = SymExecWrapper(
        contract,
        address=0x0901D12E,
        strategy="bfs",
        transaction_count=3,
        execution_timeout=300,
        modules=["AccidentallyKillable"],
    )
    issues = fire_lasers(sym, white_list=["AccidentallyKillable"])
    wall = time.time() - t0
    return sym, issues, wall


def _selects(input_hex: str, selector: int) -> bool:
    """Does this calldata dispatch to ``selector``?  EVM CALLDATALOAD
    zero-pads past calldatasize, so exact minimization may shave trailing
    zero bytes off the selector itself (0x0a11ce00 -> 3-byte calldata)."""
    data = bytes.fromhex(input_hex[2:] if input_hex.startswith("0x") else input_hex)
    padded = (data + b"\x00" * 4)[:4]
    return int.from_bytes(padded, "big") == selector


def check_recall(issues) -> None:
    assert issues, "exploit not found: zero issues"
    issue = issues[0]
    assert issue.swc_id == "106", f"wrong SWC id {issue.swc_id}"
    steps = issue.transaction_sequence["steps"]
    inputs = [s["input"] for s in steps]
    assert any(_selects(i, 0x0A11CE00) for i in inputs), "missing activate() tx"
    assert _selects(inputs[-1], 0x41C0E1B5), "final tx is not kill()"


def main() -> None:
    # the "auto" backend gates on JAX_PLATFORMS without initializing jax; on
    # machines where the TPU is autodetected but the env var is unset, pin it
    # so the measured configuration actually exercises the device hybrid
    import os

    if not os.environ.get("JAX_PLATFORMS", "").startswith(("tpu", "axon")):
        try:
            import jax

            if jax.default_backend() in ("tpu", "axon"):
                os.environ["JAX_PLATFORMS"] = jax.default_backend()
        except Exception:
            pass

    # Single sub-second runs are dominated by scheduling/solver jitter, and
    # back-to-back blocks drift with machine load — so the two
    # configurations run INTERLEAVED three times each and report median
    # rates (recall asserted on every run).  Baseline = host big-int probe
    # (the CPU solver path); measured = production hybrid (device past the
    # break-even).
    rates = {"host": [], "auto": []}
    for _ in range(3):
        for backend in ("host", "auto"):
            sym, issues, wall = run_analysis(backend)
            check_recall(issues)
            rates[backend].append(sym.laser.total_states / wall)
    base_rate = sorted(rates["host"])[1]
    rate = sorted(rates["auto"])[1]

    print(
        json.dumps(
            {
                "metric": "killbilly_3tx_states_per_sec",
                "value": round(rate, 2),
                "unit": "states/sec (production hybrid probe, exploit recall asserted)",
                "vs_baseline": round(rate / base_rate, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
